"""Mixture-of-Experts decoder with expert parallelism over an ``ep`` mesh axis.

The Mixtral-class model family the dense Llama workload doesn't cover: each
block keeps the dense attention path (model.attention_sublayer — same ring/sp
behavior) but replaces the SwiGLU MLP with a token-choice top-k router over E
experts.

TPU-first design (GShard/Switch dense-dispatch, the scaling-book MoE recipe):
routing is expressed as two einsums against a capacity-bounded one-hot
dispatch/combine tensor — static shapes, no data-dependent gather/scatter, so
XLA tiles everything onto the MXU and SPMD-partitions it. Experts carry a
leading E axis sharded over ``ep``; tokens are sharded over (dp, fsdp, ep).
The dispatch einsum's output is expert-sharded while its input is
token-sharded, which is exactly the annotation that makes XLA insert the
canonical all-to-all pair (tokens -> experts -> tokens) over ICI. Tokens
beyond an expert's capacity are dropped (standard Switch behavior); the
load-balancing auxiliary loss keeps the router from collapsing onto few
experts so drops stay rare.

Parity: the reference orchestrates MoE workloads (Mixtral examples) but ships
no parallelism of its own; this is the workload-side ep counterpart, like
model.py is for dp/fsdp/tp/sp.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import quantize as quant_lib
from dstack_tpu.workloads.config import LlamaConfig

Params = Dict[str, jax.Array]

MOE_MESH_AXES = ("dp", "fsdp", "ep", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MoeConfig(LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    # Per-expert slots = top_k * group * capacity_factor / E (rounded up): 1.0
    # is exact under perfect balance; >1 absorbs imbalance at padding cost.
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    # Routing group size (tokens): tokens are regrouped to ~this many before
    # dispatch so the [groups, group, E, C] tensors stay O(group^2) instead of
    # O(seq_len^2) — the GShard group trick. The largest divisor of the local
    # token count <= this is used.
    router_group: int = 1024

    def num_params(self) -> int:
        d, v = self.d_model, self.vocab_size
        attn = d * self.n_heads * self.head_dim + 2 * d * self.n_kv_heads * self.head_dim
        attn += self.n_heads * self.head_dim * d
        moe = self.n_experts * 3 * d * self.d_ff + d * self.n_experts  # experts + router
        per_layer = attn + moe + 2 * d
        return v * d + self.n_layers * per_layer + d + d * v

    def active_params(self) -> int:
        """Params touched per token (top_k experts) — the MoE efficiency claim."""
        d, v = self.d_model, self.vocab_size
        attn = d * self.n_heads * self.head_dim + 2 * d * self.n_kv_heads * self.head_dim
        attn += self.n_heads * self.head_dim * d
        moe = self.top_k * 3 * d * self.d_ff + d * self.n_experts
        per_layer = attn + moe + 2 * d
        return v * d + self.n_layers * per_layer + d + d * v


MOE_PRESETS = {
    "moe_test": MoeConfig(
        vocab_size=4096, d_model=256, n_layers=2, n_heads=8, n_kv_heads=4, d_ff=512,
        max_seq_len=2048, param_dtype="float32", n_experts=4, top_k=2,
    ),
    # Mixtral-8x7B-class geometry (the reference's MoE example family);
    # loss_chunk keeps [B,T,V] fp32 logits from ever materializing.
    "mixtral_8x7b": MoeConfig(
        vocab_size=32000, d_model=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        d_ff=14336, max_seq_len=8192, n_experts=8, top_k=2, loss_chunk=512,
    ),
}


def make_moe_mesh(
    dp: int = 1,
    fsdp: int = 1,
    ep: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    devices=None,
) -> Mesh:
    """(dp, fsdp, ep, tp, sp) mesh; ep=None absorbs the remaining devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if ep is None:
        denom = dp * fsdp * tp * sp
        if n % denom != 0:
            raise ValueError(f"{n} devices not divisible by dp*fsdp*tp*sp={denom}")
        ep = n // denom
    if dp * fsdp * ep * tp * sp != n:
        raise ValueError(f"mesh {dp}x{fsdp}x{ep}x{tp}x{sp} != {n} devices")
    arr = np.array(devices).reshape(dp, fsdp, ep, tp, sp)
    return Mesh(arr, MOE_MESH_AXES)


# Tokens/activations shard over ALL data-like axes (ep included — outside the
# expert computation ep behaves as extra data parallelism, so attention is
# never replicated across it); experts shard over ep, their hidden over tp.
MOE_BATCH = P(("dp", "fsdp", "ep"), "sp")
MOE_ACT = P(("dp", "fsdp", "ep"), "sp", None)

MOE_PARAM_SPECS: Dict[str, P] = {
    "embed": P("tp", ("dp", "fsdp")),
    "wq": P(None, ("dp", "fsdp"), "tp"),
    "wk": P(None, ("dp", "fsdp"), "tp"),
    "wv": P(None, ("dp", "fsdp"), "tp"),
    "wo": P(None, "tp", ("dp", "fsdp")),
    "router": P(None, None, None),                  # [L, D, E] tiny, replicated
    "w_gate": P(None, "ep", ("dp", "fsdp"), "tp"),  # [L, E, D, F]
    "w_up": P(None, "ep", ("dp", "fsdp"), "tp"),
    "w_down": P(None, "ep", "tp", ("dp", "fsdp")),  # [L, E, F, D]
    "attn_norm": P(None, None),
    "mlp_norm": P(None, None),
    "final_norm": P(None),
    "lm_head": P(("dp", "fsdp"), "tp"),
}


def init_moe_params(cfg: MoeConfig, key: jax.Array) -> Params:
    pdt = jnp.dtype(cfg.param_dtype)
    d, v, f, e = cfg.d_model, cfg.vocab_size, cfg.d_ff, cfg.n_experts
    h, kh, hd, L = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.n_layers
    keys = jax.random.split(key, 12)

    def dense(k, *shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(pdt)

    return {
        "embed": dense(keys[0], v, d, fan_in=d),
        "wq": dense(keys[1], L, d, h * hd, fan_in=d),
        "wk": dense(keys[2], L, d, kh * hd, fan_in=d),
        "wv": dense(keys[3], L, d, kh * hd, fan_in=d),
        "wo": dense(keys[4], L, h * hd, d, fan_in=h * hd),
        "router": dense(keys[5], L, d, e, fan_in=d),
        "w_gate": dense(keys[6], L, e, d, f, fan_in=d),
        "w_up": dense(keys[7], L, e, d, f, fan_in=d),
        "w_down": dense(keys[8], L, e, f, d, fan_in=f),
        "attn_norm": jnp.ones((L, d), pdt),
        "mlp_norm": jnp.ones((L, d), pdt),
        "final_norm": jnp.ones((d,), pdt),
        "lm_head": dense(keys[9], d, v, fan_in=d),
    }


def expert_capacity(cfg: MoeConfig, tokens_per_group: int) -> int:
    cap = int(np.ceil(cfg.top_k * tokens_per_group * cfg.capacity_factor / cfg.n_experts))
    return max(cap, 1)


def top_k_routing(
    router_logits: jax.Array,  # [G, S, E] fp32
    top_k: int,
    capacity: int,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(combine [G,S,E,C], dispatch [G,S,E,C] bool, aux_loss scalar).

    Token-choice top-k with per-expert capacity: each token's k chosen gates
    are renormalized; tokens claim expert slots in slot-major priority (all
    first choices before any second choice — Switch's policy) and a token that
    overflows its expert's capacity is dropped for that expert. The aux loss
    is Switch eq.4: E * sum_e(fraction_routed_e * mean_prob_e), minimized at
    uniform load."""
    g, s, e = router_logits.shape
    probs = jax.nn.softmax(router_logits, axis=-1)            # [G,S,E]
    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)         # [G,S,K]
    gate_vals = gate_vals / (jnp.sum(gate_vals, -1, keepdims=True) + 1e-9)

    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)   # [G,S,K,E]

    # Aux loss uses the FIRST choice as "routed to" (Switch counts top-1).
    frac_routed = jnp.mean(onehot[:, :, 0, :], axis=1)        # [G,E]
    mean_prob = jnp.mean(probs, axis=1)                       # [G,E]
    aux = e * jnp.mean(jnp.sum(frac_routed * mean_prob, -1))

    # Slot-major priority: flatten [K,S] so every slot-0 claim precedes any
    # slot-1 claim, then a cumulative count per expert assigns positions.
    oh_flat = onehot.transpose(0, 2, 1, 3).reshape(g, top_k * s, e)
    pos = jnp.cumsum(oh_flat, axis=1) * oh_flat - 1.0         # [G,K*S,E]
    keep = (pos >= 0) & (pos < capacity)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    disp_flat = oh_flat[..., None] * pos_oh * keep[..., None]  # [G,K*S,E,C]
    disp = disp_flat.reshape(g, top_k, s, e, capacity).transpose(0, 2, 1, 3, 4)
    gates = gate_vals[..., None, None]                         # [G,S,K,1,1]
    combine = jnp.sum(disp.reshape(g, s, top_k, e, capacity) * gates, axis=2)
    dispatch = combine > 0.0
    return combine, dispatch, aux


def moe_mlp(
    x: jax.Array,        # [B, S, D] (activation dtype)
    layer: Params,       # router [D,E], w_gate/w_up [E,D,F], w_down [E,F,D]
    cfg: MoeConfig,
    mesh: Optional[Mesh],
) -> Tuple[jax.Array, jax.Array]:
    """(out [B,S,D], aux_loss). The two dispatch einsums below are where SPMD
    inserts the token<->expert all-to-alls: x is token-sharded, expert_in is
    expert-sharded. Tokens are regrouped to ~router_group before dispatch so
    the one-hot tensors scale with the group size, not the sequence length."""
    adt = x.dtype
    b, s, d = x.shape
    group = next(
        (c for c in range(min(cfg.router_group, s), 0, -1) if s % c == 0), s
    )
    g = b * (s // group)
    x = x.reshape(g, group, d)
    cap = expert_capacity(cfg, group)

    # Routing stays full-precision under quant=int8 (a mis-rounded router
    # flips token->expert assignments, which costs far more than the matmul
    # flops it would save); the expert matmuls below fake-quantize their
    # weights to the int8 grid with straight-through gradients — the
    # einsum-shaped path for per-expert [E, D, F] tensors that the dense
    # model's int8 dot_general can't express (quantize.fake_quant).
    router_logits = jnp.einsum(
        "gsd,de->gse", x, layer["router"].astype(adt),
        preferred_element_type=jnp.float32,
    )
    combine, dispatch, aux = top_k_routing(router_logits, cfg.top_k, cap)

    def expert_w(key: str) -> jax.Array:
        w = layer[key].astype(adt)
        if cfg.quant == "int8":
            w = quant_lib.fake_quant(w, axis=1)  # contraction dim of [E, K, N]
        return w

    def constrain(a, spec):
        if mesh is None:
            return a
        return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))

    # Grouped tensors shard their group dim over the data axes (the group dim
    # folds batch x sequence-chunks, so sp stays out of these specs).
    combine = constrain(combine, P(("dp", "fsdp", "ep"), None, None, None))

    # tokens -> experts (all-to-all over ep happens here)
    expert_in = jnp.einsum("gsec,gsd->egcd", dispatch.astype(adt), x)
    expert_in = constrain(expert_in, P("ep", ("dp", "fsdp"), None, None))

    gate = jnp.einsum("egcd,edf->egcf", expert_in, expert_w("w_gate"),
                      preferred_element_type=jnp.float32).astype(adt)
    up = jnp.einsum("egcd,edf->egcf", expert_in, expert_w("w_up"),
                    preferred_element_type=jnp.float32).astype(adt)
    hidden = jax.nn.silu(gate.astype(jnp.float32)).astype(adt) * up
    hidden = constrain(hidden, P("ep", ("dp", "fsdp"), None, "tp"))
    expert_out = jnp.einsum("egcf,efd->egcd", hidden, expert_w("w_down"),
                            preferred_element_type=jnp.float32).astype(adt)
    expert_out = constrain(expert_out, P("ep", ("dp", "fsdp"), None, None))

    # experts -> tokens (the return all-to-all), weighted by the gates
    out = jnp.einsum("gsec,egcd->gsd", combine.astype(adt), expert_out)
    out = out.reshape(b, s, d)
    return constrain(out, MOE_ACT), aux


def forward(
    params: Params,
    tokens: jax.Array,  # [G, S]
    cfg: MoeConfig,
    mesh: Optional[Mesh] = None,
    return_hidden: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """(logits [G,S,V] fp32, total_aux_loss) — or (hidden [G,S,D], aux) when
    `return_hidden` (feeds the chunked cross-entropy)."""
    adt = jnp.dtype(cfg.dtype)
    t = tokens.shape[1]

    def constrain(a, spec):
        if mesh is None:
            return a
        return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))

    # Embedding: the dense model's partitioned lookup (vocab tp-sharded; a
    # plain gather would trigger SPMD's involuntary full rematerialization);
    # ep joins the batch axes.
    x = model_lib._embed_lookup(
        params["embed"], tokens, mesh, adt, batch_axes=("dp", "fsdp", "ep")
    )
    x = constrain(x, MOE_ACT)
    positions = jnp.arange(t)

    def block(x, layer):
        # Same attention path as the dense model, with ep in the batch axes so
        # ring attention (sp>1) and the flash-vs-mesh guard behave identically.
        x = model_lib.attention_sublayer(
            x, layer, cfg, positions, mesh, constrain,
            batch_axes=("dp", "fsdp", "ep"),
        )
        x = constrain(x, MOE_ACT)
        h = model_lib._rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        moe_out, aux = moe_mlp(h, layer, cfg, mesh)
        return x + moe_out, aux

    block_fn = (
        jax.checkpoint(block, prevent_cse=True,
                       policy=model_lib.remat_policy_of(cfg))
        if cfg.remat else block
    )

    layer_params = {
        k: params[k]
        for k in ("wq", "wk", "wv", "wo", "router", "w_gate", "w_up", "w_down",
                  "attn_norm", "mlp_norm")
    }

    def scan_body(x, layer):
        x, aux = block_fn(x, layer)
        return x, aux

    x, aux_per_layer = jax.lax.scan(scan_body, x, layer_params)
    x = model_lib._rms_norm(x, params["final_norm"], cfg.norm_eps)
    aux_total = jnp.sum(aux_per_layer)
    if return_hidden:
        return x, aux_total
    logits = jnp.einsum("gsd,dv->gsv", x, params["lm_head"].astype(adt),
                        preferred_element_type=jnp.float32)
    return constrain(logits, MOE_ACT), aux_total


def loss_fn(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: MoeConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    chunk = model_lib.pick_loss_chunk(cfg, tokens.shape[1])
    if chunk:
        hidden, aux = forward(params, tokens, cfg, mesh, return_hidden=True)
        lm_head = params["lm_head"].astype(jnp.dtype(cfg.dtype))
        total_nll, total_cnt = model_lib._chunked_nll(hidden, lm_head, targets, chunk)
        ce = total_nll / jnp.maximum(total_cnt, 1)
    else:
        logits, aux = forward(params, tokens, cfg, mesh)
        ce = model_lib.masked_ce(logits, targets)
    return ce + cfg.aux_loss_weight * aux


def moe_param_sharding(mesh: Mesh) -> Dict[str, NamedSharding]:
    return {k: NamedSharding(mesh, spec) for k, spec in MOE_PARAM_SPECS.items()}


def shard_moe_params(params: Params, mesh: Mesh) -> Params:
    shardings = moe_param_sharding(mesh)
    return {k: jax.device_put(v, shardings[k]) for k, v in params.items()}


def make_moe_train_step(
    cfg: MoeConfig,
    optimizer,
    mesh: Optional[Mesh] = None,
    grad_accum: int = 1,
):
    """jitted (params, opt_state, tokens, targets) -> (params, opt_state, loss).

    `grad_accum=N` scans N microbatches with fp32 grad accumulators (same
    recipe as the dense step — train.accumulate_grads); donation and the
    explicit batch shardings are unchanged."""
    import optax

    from dstack_tpu.workloads import train

    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    data_shards = (
        mesh.shape["dp"] * mesh.shape["fsdp"] * mesh.shape["ep"]
        if mesh is not None else 1
    )

    def micro_constraint(x):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, ("dp", "fsdp", "ep"), "sp"))
        )

    def step(params, opt_state, tokens, targets):
        train.check_microbatch(tokens.shape[0], grad_accum, data_shards,
                               axes_label="dp*fsdp*ep")
        if grad_accum == 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets, cfg, mesh)
        else:
            loss, grads = train.accumulate_grads(
                loss_fn, params, tokens, targets, grad_accum,
                micro_constraint=micro_constraint, cfg=cfg, mesh=mesh,
            )
        updates, new_opt = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt, loss

    if mesh is None:
        return jax.jit(step, donate_argnums=(0, 1))
    bspec = NamedSharding(mesh, MOE_BATCH)
    return jax.jit(step, donate_argnums=(0, 1),
                   in_shardings=(None, None, bspec, bspec))
