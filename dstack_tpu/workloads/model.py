"""Llama-style decoder in pure functional JAX, sharded via NamedSharding constraints.

TPU-first choices:
- layer weights are stacked on a leading axis and the block runs under ``lax.scan`` —
  one compiled block regardless of depth (fast compile, XLA-friendly);
- activations stay bfloat16, matmuls hit the MXU with fp32 accumulation
  (``preferred_element_type``);
- per-block rematerialization (``jax.checkpoint``) trades FLOPs for HBM;
- attention is blockwise/ring (attention.py) so long context never materializes T².

Parity: the MaxText-analog workload for the reference's distributed-training examples
(reference examples/distributed-training; BASELINE.json north star).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dstack_tpu.workloads.attention import blockwise_attention, ring_attention
from dstack_tpu.workloads.config import LlamaConfig

Params = Dict[str, jax.Array]


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Initialize the stacked-layer parameter tree (shapes documented in
    sharding.PARAM_SPECS)."""
    pdt = jnp.dtype(cfg.param_dtype)
    d, v, f = cfg.d_model, cfg.vocab_size, cfg.d_ff
    h, kh, hd, L = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.n_layers
    keys = jax.random.split(key, 10)

    def norm_init(*shape):
        return jnp.ones(shape, pdt)

    def dense_init(k, *shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(pdt)

    return {
        "embed": dense_init(keys[0], v, d, fan_in=d),
        "wq": dense_init(keys[1], L, d, h * hd, fan_in=d),
        "wk": dense_init(keys[2], L, d, kh * hd, fan_in=d),
        "wv": dense_init(keys[3], L, d, kh * hd, fan_in=d),
        "wo": dense_init(keys[4], L, h * hd, d, fan_in=h * hd),
        "w_gate": dense_init(keys[5], L, d, f, fan_in=d),
        "w_up": dense_init(keys[6], L, d, f, fan_in=d),
        "w_down": dense_init(keys[7], L, f, d, fan_in=f),
        "attn_norm": norm_init(L, d),
        "mlp_norm": norm_init(L, d),
        "final_norm": norm_init(d),
        "lm_head": dense_init(keys[8], d, v, fan_in=d),
    }


def _rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x [B,T,H,D], positions [T] (global, so sequence-parallel
    chunks rotate correctly)."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def forward(
    params: Params,
    tokens: jax.Array,  # [B, T] int32
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Returns logits [B, T, V] (float32). When `mesh` is given, activation sharding
    constraints are inserted and attention runs ring-parallel over `sp`."""
    adt = jnp.dtype(cfg.dtype)
    b, t = tokens.shape
    use_sp = mesh is not None and mesh.shape.get("sp", 1) > 1

    def act_constraint(x, spec):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    x = params["embed"].astype(adt)[tokens]  # [B,T,D]
    x = act_constraint(x, P(("dp", "fsdp"), "sp", None))
    positions = jnp.arange(t)

    def block(x, layer):
        h_in = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("btd,dk->btk", h_in, layer["wq"].astype(adt),
                       preferred_element_type=jnp.float32).astype(adt)
        k = jnp.einsum("btd,dk->btk", h_in, layer["wk"].astype(adt),
                       preferred_element_type=jnp.float32).astype(adt)
        v = jnp.einsum("btd,dk->btk", h_in, layer["wv"].astype(adt),
                       preferred_element_type=jnp.float32).astype(adt)
        q = q.reshape(b, t, cfg.n_heads, cfg.head_dim)
        k = k.reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        v = v.reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
        q = act_constraint(q, P(("dp", "fsdp"), "sp", "tp", None))
        k = act_constraint(k, P(("dp", "fsdp"), "sp", "tp", None))
        v = act_constraint(v, P(("dp", "fsdp"), "sp", "tp", None))
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        if use_sp:
            o = ring_attention(q, k, v, mesh)
        else:
            o = blockwise_attention(q, k, v)
        o = o.astype(adt).reshape(b, t, cfg.n_heads * cfg.head_dim)
        attn_out = jnp.einsum("btk,kd->btd", o, layer["wo"].astype(adt),
                              preferred_element_type=jnp.float32).astype(adt)
        x = x + act_constraint(attn_out, P(("dp", "fsdp"), "sp", None))

        h2 = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = jnp.einsum("btd,df->btf", h2, layer["w_gate"].astype(adt),
                          preferred_element_type=jnp.float32)
        up = jnp.einsum("btd,df->btf", h2, layer["w_up"].astype(adt),
                        preferred_element_type=jnp.float32)
        hidden = (jax.nn.silu(gate) * up).astype(adt)
        hidden = act_constraint(hidden, P(("dp", "fsdp"), "sp", "tp"))
        mlp_out = jnp.einsum("btf,fd->btd", hidden, layer["w_down"].astype(adt),
                             preferred_element_type=jnp.float32).astype(adt)
        x = x + act_constraint(mlp_out, P(("dp", "fsdp"), "sp", None))
        return x

    layer_params = {
        k: params[k]
        for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "attn_norm", "mlp_norm")
    }
    block_fn = block
    if cfg.remat:
        policy = None
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        block_fn = jax.checkpoint(block, prevent_cse=True, policy=policy)

    def scan_body(x, layer):
        return block_fn(x, layer), None

    x, _ = jax.lax.scan(scan_body, x, layer_params)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = jnp.einsum("btd,dv->btv", x, params["lm_head"].astype(adt),
                        preferred_element_type=jnp.float32)
    return act_constraint(logits, P(("dp", "fsdp"), "sp", None))


def loss_fn(
    params: Params,
    tokens: jax.Array,   # [B, T]
    targets: jax.Array,  # [B, T]; -1 = ignore
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    logits = forward(params, tokens, cfg, mesh)
    mask = targets >= 0
    safe_targets = jnp.where(mask, targets, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)
