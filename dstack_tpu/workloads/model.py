"""Llama-style decoder in pure functional JAX, sharded via NamedSharding constraints.

TPU-first choices:
- layer weights are stacked on a leading axis and the block runs under ``lax.scan`` —
  one compiled block regardless of depth (fast compile, XLA-friendly);
- activations stay bfloat16, matmuls hit the MXU with fp32 accumulation
  (``preferred_element_type``);
- per-block rematerialization (``jax.checkpoint``) trades FLOPs for HBM;
- attention is blockwise/ring (attention.py) so long context never materializes T².

Parity: the MaxText-analog workload for the reference's distributed-training examples
(reference examples/distributed-training; BASELINE.json north star).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dstack_tpu.workloads import quantize as quant_lib
from dstack_tpu.workloads.attention import attention_core
from dstack_tpu.workloads.config import LlamaConfig
from dstack_tpu.workloads.kernels.collective import (
    allgather_matmul,
    can_fsdp_overlap,
    can_overlap,
    collective_matmul,
)

Params = Dict[str, jax.Array]


def dense_proj(x: jax.Array, w: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """``x[..., K] @ w[K, N]`` in the activation dtype, under cfg.quant:
    the fp path is the einsum-with-fp32-accumulation every projection used
    before; ``int8`` runs the dynamically-quantized STE dot."""
    return quant_lib.matmul(x, w, cfg.quant, adt=x.dtype)


def down_proj(
    x: jax.Array,   # [B, T, K] — K (heads/ff hidden) tp-sharded
    w: jax.Array,   # [K, D]
    cfg: LlamaConfig,
    mesh: Optional[Mesh],
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
) -> jax.Array:
    """The TP down-projections (wo, w_down): contraction dim tp-sharded, so
    XLA's plain path is matmul-then-all-reduce. With cfg.tp_overlap the
    collective-matmul ring (kernels/collective.py) hides that all-reduce
    under the partial matmuls; falls back to the plain path when the ring
    doesn't divide (validate_config raises loudly for CLI-requested combos).
    """
    if (
        cfg.tp_overlap
        and mesh is not None
        and mesh.shape.get("tp", 1) > 1
        and can_overlap(mesh, x.shape[0], x.shape[1], batch_axes=batch_axes)
    ):
        return collective_matmul(
            x, w, mesh, batch_axes=batch_axes, matmul=_quant_partial_mm(cfg)
        ).astype(x.dtype)
    return dense_proj(x, w, cfg)


def _quant_partial_mm(cfg: LlamaConfig):
    """The per-chunk matmul for a collective ring under cfg.quant (None = fp
    dot): STE dots so partials quantize with per-chunk scales and the ring
    stays differentiable."""
    if cfg.quant == "int8":
        return quant_lib.int8_matmul_ste
    if cfg.quant == "fp8":
        return quant_lib.fp8_matmul_ste
    return None


def up_proj(
    x: jax.Array,   # [B, T, D] — batch over (dp, fsdp), D replicated
    w: jax.Array,   # [D, N]    — D fsdp-sharded, N tp-sharded
    cfg: LlamaConfig,
    mesh: Optional[Mesh],
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
) -> jax.Array:
    """The FSDP column-parallel up-projections (wq/wk/wv/w_gate/w_up):
    contraction dim (d_model) sharded over (dp, fsdp), so XLA's plain path
    all-gathers the whole [D, N] weight before the matmul can start. With
    cfg.fsdp_overlap the all-gather ring (kernels/collective.py) rotates
    weight shards around the data axes instead, each hop hiding under the
    previous chunk's matmul; falls back to the plain path when shapes don't
    divide (validate_config raises loudly for CLI-requested combos)."""
    if cfg.fsdp_overlap and mesh is not None:
        data = 1
        for a in batch_axes:
            data *= mesh.shape.get(a, 1)
        sp = mesh.shape.get("sp", 1)
        tp = mesh.shape.get("tp", 1)
        if (
            can_fsdp_overlap(mesh, x.shape[-1], batch_axes)
            and x.shape[0] % data == 0
            and x.shape[1] % sp == 0
            and w.shape[-1] % tp == 0
        ):
            return allgather_matmul(
                x, w, mesh, batch_axes=batch_axes,
                matmul=_quant_partial_mm(cfg),
            ).astype(x.dtype)
    return dense_proj(x, w, cfg)


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Initialize the stacked-layer parameter tree (shapes documented in
    sharding.PARAM_SPECS)."""
    pdt = jnp.dtype(cfg.param_dtype)
    d, v, f = cfg.d_model, cfg.vocab_size, cfg.d_ff
    h, kh, hd, L = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.n_layers
    keys = jax.random.split(key, 10)

    def norm_init(*shape):
        return jnp.ones(shape, pdt)

    def dense_init(k, *shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)).astype(pdt)

    return {
        "embed": dense_init(keys[0], v, d, fan_in=d),
        "wq": dense_init(keys[1], L, d, h * hd, fan_in=d),
        "wk": dense_init(keys[2], L, d, kh * hd, fan_in=d),
        "wv": dense_init(keys[3], L, d, kh * hd, fan_in=d),
        "wo": dense_init(keys[4], L, h * hd, d, fan_in=h * hd),
        "w_gate": dense_init(keys[5], L, d, f, fan_in=d),
        "w_up": dense_init(keys[6], L, d, f, fan_in=d),
        "w_down": dense_init(keys[7], L, f, d, fan_in=f),
        "attn_norm": norm_init(L, d),
        "mlp_norm": norm_init(L, d),
        "final_norm": norm_init(d),
        "lm_head": dense_init(keys[8], d, v, fan_in=d),
    }


def _rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w.astype(x.dtype)


def _embed_lookup(
    embed: jax.Array,
    tokens: jax.Array,
    mesh: Optional[Mesh],
    adt,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
) -> jax.Array:
    """Token embedding lookup, partition-aware.

    Single-device: a plain gather. Under a mesh the table is vocab-sharded over
    ``tp`` (sharding.PARAM_SPECS) and SPMD cannot partition a gather whose
    operand is sharded on the indexed dim — it falls back to "involuntary full
    rematerialization": an all-gather of the entire table in the hot path every
    step. Do the partitioned lookup explicitly instead: all-gather the table's
    D axis (the standard FSDP gather-on-use, same as every other weight), then
    each tp shard masks-and-gathers its local vocab rows and the partial
    results psum over tp — one [b,t,D] psum on ICI instead of a [V,D] table
    all-gather."""
    if mesh is None:
        return embed.astype(adt)[tokens]
    from jax.experimental.shard_map import shard_map

    v = embed.shape[0]
    tp = mesh.shape.get("tp", 1)
    if tp == 1:
        # Vocab unsharded: a plain gather partitions fine (only the [B,T,D]
        # result moves), so constrain just the output.
        return jax.lax.with_sharding_constraint(
            embed.astype(adt)[tokens],
            NamedSharding(mesh, P(batch_axes, "sp", None)),
        )
    if v % tp != 0:
        # tp-sharded but indivisible vocab: SPMD would replicate the table as a
        # last resort anyway — do it explicitly so the cost is visible and the
        # compiler never warns.
        emb = jax.lax.with_sharding_constraint(
            embed.astype(adt), NamedSharding(mesh, P(None, None))
        )
        return jax.lax.with_sharding_constraint(
            emb[tokens], NamedSharding(mesh, P(batch_axes, "sp", None))
        )
    v_loc = v // tp
    emb = jax.lax.with_sharding_constraint(
        embed.astype(adt), NamedSharding(mesh, P("tp", None))
    )

    def local_lookup(emb_block, tok_block):
        lo = jax.lax.axis_index("tp") * v_loc
        local = tok_block - lo
        ok = (local >= 0) & (local < v_loc)
        rows = emb_block[jnp.clip(local, 0, v_loc - 1)]
        rows = jnp.where(ok[..., None], rows, jnp.zeros((), rows.dtype))
        return jax.lax.psum(rows, "tp")

    return shard_map(
        local_lookup,
        mesh=mesh,
        in_specs=(P("tp", None), P(batch_axes, "sp")),
        out_specs=P(batch_axes, "sp", None),
    )(emb, tokens)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x [B,T,H,D], positions [T] (global, so sequence-parallel
    chunks rotate correctly)."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def attention_sublayer(
    x: jax.Array,
    layer: Params,
    cfg: LlamaConfig,
    positions: jax.Array,
    mesh: Optional[Mesh],
    act_constraint,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
) -> jax.Array:
    """Pre-norm attention + residual. Module-level so the pipeline-parallel
    stage (pipeline.py) and the MoE decoder (moe.py) run the exact same
    attention path as the dense model. `batch_axes` names the mesh axes the
    batch dim is sharded over (MoE adds "ep")."""
    adt = x.dtype
    b, t = x.shape[0], x.shape[1]
    name = checkpoint_name

    h_in = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = name(up_proj(h_in, layer["wq"], cfg, mesh, batch_axes), "proj")
    k = name(up_proj(h_in, layer["wk"], cfg, mesh, batch_axes), "proj")
    v = name(up_proj(h_in, layer["wv"], cfg, mesh, batch_axes), "proj")
    q = q.reshape(b, t, cfg.n_heads, cfg.head_dim)
    k = k.reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(b, t, cfg.n_kv_heads, cfg.head_dim)
    q = act_constraint(q, P(batch_axes, "sp", "tp", None))
    k = act_constraint(k, P(batch_axes, "sp", "tp", None))
    v = act_constraint(v, P(batch_axes, "sp", "tp", None))
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    o = attention_core(q, k, v, cfg.attn_impl, mesh, batch_axes=batch_axes,
                       window=cfg.attn_window)
    o = name(o.astype(adt).reshape(b, t, cfg.n_heads * cfg.head_dim), "proj")
    attn_out = down_proj(o, layer["wo"], cfg, mesh, batch_axes).astype(adt)
    return x + act_constraint(attn_out, P(batch_axes, "sp", None))


def transformer_block(
    x: jax.Array,
    layer: Params,
    cfg: LlamaConfig,
    positions: jax.Array,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """One dense decoder block (attention + SwiGLU MLP, both pre-norm residual)."""
    adt = x.dtype
    name = checkpoint_name

    def act_constraint(a, spec):
        if mesh is None:
            return a
        return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))

    x = attention_sublayer(x, layer, cfg, positions, mesh, act_constraint)

    h2 = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    gate = name(up_proj(h2, layer["w_gate"], cfg, mesh), "proj")
    up = name(up_proj(h2, layer["w_up"], cfg, mesh), "proj")
    hidden = jax.nn.silu(gate.astype(jnp.float32)).astype(adt) * up
    hidden = act_constraint(hidden, P(("dp", "fsdp"), "sp", "tp"))
    mlp_out = down_proj(hidden, layer["w_down"], cfg, mesh).astype(adt)
    return x + act_constraint(mlp_out, P(("dp", "fsdp"), "sp", None))


def remat_policy_of(cfg: LlamaConfig):
    """cfg.remat_policy -> jax.checkpoint policy, shared by the dense, MoE,
    and pipeline forwards so one config means one HBM/recompute profile.
    "save_proj" saves the projection-matmul outputs (checkpoint-named "proj"
    in attention_sublayer/transformer_block); backward then re-runs only
    cheap elementwise ops + the score matmuls."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy == "save_proj":
        return jax.checkpoint_policies.save_only_these_names("proj")
    return None


def forward(
    params: Params,
    tokens: jax.Array,  # [B, T] int32
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
    return_hidden: bool = False,
) -> jax.Array:
    """Returns logits [B, T, V] (float32), or the final hidden state [B, T, D]
    (post final_norm, pre lm_head) when `return_hidden` — used by the chunked
    cross-entropy so [B,T,V] fp32 logits are never fully materialized. When
    `mesh` is given, activation sharding constraints are inserted and attention
    runs ring-parallel over `sp`."""
    adt = jnp.dtype(cfg.dtype)
    t = tokens.shape[1]

    def act_constraint(x, spec):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    x = _embed_lookup(params["embed"], tokens, mesh, adt)  # [B,T,D]
    x = act_constraint(x, P(("dp", "fsdp"), "sp", None))
    positions = jnp.arange(t)

    def block(x, layer):
        return transformer_block(x, layer, cfg, positions, mesh)

    layer_params = {
        k: params[k]
        for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "attn_norm", "mlp_norm")
    }
    block_fn = block
    if cfg.remat:
        block_fn = jax.checkpoint(block, prevent_cse=True, policy=remat_policy_of(cfg))

    def scan_body(x, layer):
        return block_fn(x, layer), None

    x, _ = jax.lax.scan(scan_body, x, layer_params)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    if return_hidden:
        return x
    if cfg.quant == "int8":
        logits = quant_lib.int8_matmul_ste(x, params["lm_head"])
    else:
        logits = jnp.einsum("btd,dv->btv", x, params["lm_head"].astype(adt),
                            preferred_element_type=jnp.float32)
    return act_constraint(logits, P(("dp", "fsdp"), "sp", None))


def _chunked_nll(
    x: jax.Array,        # [B, T, D] final hidden (post final_norm)
    lm_head: jax.Array,  # [D, V]
    targets: jax.Array,  # [B, T]; -1 = ignore
    chunk: int,
    quant: str = "none",
) -> Tuple[jax.Array, jax.Array]:
    """Cross-entropy without materializing [B,T,V] fp32 logits: scan the sequence
    in chunks; each chunk's logits+log_softmax live only inside its scan step and
    are rematerialized on the backward pass (jax.checkpoint)."""
    b, t, d = x.shape
    n_chunks = t // chunk

    xc = x.reshape(b, n_chunks, chunk, d).swapaxes(0, 1)        # [n,B,c,D]
    tc = targets.reshape(b, n_chunks, chunk).swapaxes(0, 1)     # [n,B,c]

    @jax.checkpoint
    def chunk_nll(x_blk, t_blk):
        if quant == "int8":
            logits = quant_lib.int8_matmul_ste(x_blk, lm_head)
        else:
            logits = jnp.einsum("bcd,dv->bcv", x_blk, lm_head,
                                preferred_element_type=jnp.float32)
        mask = t_blk >= 0
        safe = jnp.where(mask, t_blk, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        return jnp.sum(nll * mask), jnp.sum(mask)

    def body(carry, inputs):
        s_nll, s_cnt = carry
        x_blk, t_blk = inputs
        nll, cnt = chunk_nll(x_blk, t_blk)
        return (s_nll + nll, s_cnt + cnt), None

    (total_nll, total_cnt), _ = jax.lax.scan(body, (jnp.zeros(()), jnp.zeros((), jnp.int32)), (xc, tc))
    return total_nll, total_cnt


def pick_loss_chunk(cfg: LlamaConfig, seq_len: int) -> int:
    """Largest divisor of seq_len that is <= cfg.loss_chunk, keeping the
    chunked path (and its HBM saving) for any length; 0 = use full logits
    (either loss_chunk is off or no usable divisor exists)."""
    if not cfg.loss_chunk:
        return 0
    chunk = next(
        (c for c in range(min(cfg.loss_chunk, seq_len), 0, -1)
         if seq_len % c == 0),
        1,
    )
    if chunk < max(1, cfg.loss_chunk // 8):
        import warnings

        warnings.warn(
            f"loss_chunk={cfg.loss_chunk} has no usable divisor of seq_len="
            f"{seq_len} (best {chunk}); falling back to full logits",
            stacklevel=3,
        )
        return 0
    return chunk


# ---------------------------------------------------------------------------
# Speculative-decode draft head (EAGLE-style conditioning, self-contained).
#
# The head proposes the target model's NEXT-next token from two inputs it gets
# for free on the decode path: the target's last hidden state (post final_norm,
# pre lm_head — the same [D] vector the lm_head just consumed) and the
# embedding of the token that hidden state emitted. Both are fused through a
# [2D, D] projection, refined by a short stack of pre-norm residual SwiGLU
# blocks, and projected through the TARGET's lm_head — the head never owns a
# vocab-sized matrix, which is what keeps it small enough to replicate on a
# tp-sharded serve mesh.
#
# The blocks are deliberately attention-free: the conditioning hidden state
# already summarizes the full attended context, so the head carries no KV cache
# of its own — serve-side preemption and re-prefill need no head-state rebuild,
# and a k-token proposal is one tiny jitted scan (serve.make_draft_fn). Drafts
# remain a pure throughput bet: the engine's verify forward scores them, so a
# bad head costs acceptance, never correctness.


def init_draft_params(
    cfg: LlamaConfig, key: jax.Array, n_layers: int = 2, d_ff: int = 0
) -> Params:
    """Draft-head parameter tree (stacked layers, scanned like the target).
    ``d_ff`` defaults to 2*d_model — the head is ~n_layers * 6*D^2 params,
    orders of magnitude under the target it drafts for."""
    pdt = jnp.dtype(cfg.param_dtype)
    d = cfg.d_model
    f = d_ff or 2 * d
    L = n_layers
    keys = jax.random.split(key, 4)

    def dense_init(k, *shape, fan_in):
        return (
            jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)
        ).astype(pdt)

    return {
        "w_fuse": dense_init(keys[0], 2 * d, d, fan_in=2 * d),
        "mlp_norm": jnp.ones((L, d), pdt),
        "w_gate": dense_init(keys[1], L, d, f, fan_in=d),
        "w_up": dense_init(keys[2], L, d, f, fan_in=d),
        "w_down": dense_init(keys[3], L, f, d, fan_in=f),
        "final_norm": jnp.ones((d,), pdt),
    }


def _draft_mm(x: jax.Array, w: jax.Array) -> jax.Array:
    return jax.lax.dot_general(
        x, w.astype(x.dtype), (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)


def draft_apply(
    draft: Params, hidden: jax.Array, tok_emb: jax.Array, cfg: LlamaConfig
) -> jax.Array:
    """One head application: (target hidden [..., D], condition-token
    embedding [..., D]) -> predicted next hidden [..., D], in the same basis
    the target's lm_head reads (post final_norm). Works on any leading shape —
    [S, D] rows on the serve path, [B, T, D] teacher-forced sequences in
    distillation."""
    x = _draft_mm(jnp.concatenate([hidden, tok_emb], axis=-1), draft["w_fuse"])

    def block(x, layer):
        h2 = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = _draft_mm(h2, layer["w_gate"])
        up = _draft_mm(h2, layer["w_up"])
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(x.dtype) * up
        return x + _draft_mm(act, layer["w_down"]), None

    layer_params = {
        k: draft[k] for k in ("mlp_norm", "w_gate", "w_up", "w_down")
    }
    x, _ = jax.lax.scan(block, x, layer_params)
    return _rms_norm(x, draft["final_norm"], cfg.norm_eps)


def draft_propose(
    params: Params,
    draft: Params,
    hidden: jax.Array,       # [S, D] target hidden at each row's last position
    last_tokens: jax.Array,  # [S] the token that hidden state emitted
    k: int,
    cfg: LlamaConfig,
) -> jax.Array:
    """k greedy draft tokens per row in one scan, [S, k] int32: each step
    embeds the previous token (the target's embed table), applies the head,
    and reads the argmax through the target's lm_head; the head's own output
    hidden becomes the next step's conditioning. The fp reference for
    serve.make_draft_fn (which adds weight-only-quant lm_head handling)."""
    adt = jnp.dtype(cfg.dtype)

    def step(carry, _):
        h, t = carry
        e = params["embed"].astype(adt)[t]
        h2 = draft_apply(draft, h.astype(adt), e, cfg)
        logits = _draft_mm(h2, params["lm_head"]).astype(jnp.float32)
        nt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (h2, nt), nt

    _, drafts = jax.lax.scan(
        step, (hidden.astype(adt), last_tokens.astype(jnp.int32)), None,
        length=k,
    )
    return jnp.swapaxes(drafts, 0, 1)  # [S, k]


def draft_distill_loss(
    draft: Params,
    params: Params,
    tokens: jax.Array,  # [B, T]
    cfg: LlamaConfig,
    rollout: int = 2,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Distillation loss vs the FROZEN target on one batch: cross-entropy of
    the head's prediction against the target's own argmax (train.py
    --draft-head; gradients flow into ``draft`` only — callers differentiate
    argnums=0).

    Position t conditions on (target hidden_t, embedding of token_{t+1}) and
    must predict the target's argmax at t+1 — exactly the serve-time contract,
    where the condition token IS that argmax (greedy decode). ``rollout``
    extends the loss to the head's own continuations: step j >= 2 feeds the
    head its previous output hidden and proposed token (what proposal
    positions 2..k see at serve time), labeled with the target argmax j ahead;
    without it, later draft positions would be trained on nothing."""
    adt = jnp.dtype(cfg.dtype)
    t = tokens.shape[1]
    hidden = forward(params, tokens, cfg, mesh, return_hidden=True)  # [B,T,D]
    tgt_logits = _draft_mm(hidden, params["lm_head"]).astype(jnp.float32)
    labels = jnp.argmax(tgt_logits, axis=-1)  # [B, T]: a_t
    labels = jax.lax.stop_gradient(labels)
    hidden = jax.lax.stop_gradient(hidden)

    h = hidden[:, :-1]                     # rows t = 0..T-2
    cond = tokens[:, 1:].astype(jnp.int32)  # x_{t+1}
    total = jnp.zeros(())
    for j in range(1, rollout + 1):
        e = params["embed"].astype(adt)[cond]
        h = draft_apply(draft, h, e, cfg)
        logits_j = _draft_mm(h, params["lm_head"]).astype(jnp.float32)
        # Row t's label at rollout depth j is a_{t+j}; rows past T-1-j have
        # no label — mask with -1 (masked_ce's ignore value).
        lab = jnp.pad(
            labels[:, j:], ((0, 0), (0, j - 1)), constant_values=-1
        )
        total = total + masked_ce(logits_j, lab)
        cond = jnp.argmax(logits_j, axis=-1).astype(jnp.int32)
    return total / rollout


def masked_ce(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean cross-entropy over targets >= 0 (-1 = ignore); logits fp32."""
    mask = targets >= 0
    safe_targets = jnp.where(mask, targets, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)


def loss_fn(
    params: Params,
    tokens: jax.Array,   # [B, T]
    targets: jax.Array,  # [B, T]; -1 = ignore
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    chunk = pick_loss_chunk(cfg, tokens.shape[1])
    if chunk:
        hidden = forward(params, tokens, cfg, mesh, return_hidden=True)
        lm_head = params["lm_head"].astype(jnp.dtype(cfg.dtype))
        total_nll, total_cnt = _chunked_nll(hidden, lm_head, targets, chunk,
                                            quant=cfg.quant)
        return total_nll / jnp.maximum(total_cnt, 1)
    return masked_ce(forward(params, tokens, cfg, mesh), targets)
