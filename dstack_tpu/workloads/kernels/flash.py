"""In-repo Pallas flash attention: block-tiled online softmax, fwd + bwd.

The memory-bound half of the 52%-MFU plateau (ROADMAP item 4): the XLA
blockwise scan keeps scores out of HBM but still round-trips the online-softmax
state through layout shuffles XLA chooses; this kernel owns the tiling.
Layout mirrors the public ``jax.experimental.pallas.ops.tpu.flash_attention``
([B*H, T, D] with one (batch·head, q-block) program per grid cell) but the
backward pass is in-repo too (custom VJP, separate dq and dk/dv kernels), so
``interpret=True`` runs the *identical* code CPU-side — tier-1 tests assert
fwd+grad equivalence against ``blockwise_attention`` to 1e-4.

Differences vs the public kernel worth knowing:
- GQA never materializes repeated KV: q rows for one KV head are contiguous
  after the [B*H, T, D] reshape (head = group·n_rep + rep), so the forward/dq
  index maps point program b at KV row b // n_rep, and the dk/dv grid streams
  each KV row's n_rep q rows block-by-block into a resident accumulator —
  same head convention as ``attention._repeat_kv``, none of the n_rep× KV
  HBM traffic.
- Sequence lengths must divide the chosen block sizes; ``pick_flash_block``
  picks the largest power-of-two block that fits, and the dispatcher
  (attention.attention_core) falls back to blockwise when none does.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30

# Candidate block edges, largest first. 128 matches the MXU tile; smaller
# blocks only exist so tiny CPU-test shapes can run the same kernel.
_BLOCKS = (512, 256, 128, 64, 32, 16, 8)


def pick_flash_block(seq_len: int, cap: int = 512) -> Optional[int]:
    """Largest candidate block <= cap that divides seq_len (None when none
    does — e.g. prime lengths — in which case flash cannot run)."""
    for b in _BLOCKS:
        if b <= cap and seq_len >= b and seq_len % b == 0:
            return b
    return None


from dstack_tpu.workloads.kernels.platform import use_interpret as _use_interpret


# ---------------------------------------------------------------------------
# forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, block_q,
                block_k, scale):
    """One (batch·head, q-block) program: online softmax over KV blocks.

    Refs: q [1, block_q, D]; k/v [1, S, D]; o [1, block_q, D]; lse [1, block_q].
    """
    iq = pl.program_id(1)
    s_len = k_ref.shape[1]
    d = q_ref.shape[-1]
    q = q_ref[0].astype(jnp.float32)  # [bq, D]

    n_kv = s_len // block_k
    if causal:
        # Only blocks whose first position can be <= the last q position.
        hi = (iq * block_q + block_q + block_k - 1) // block_k
        hi = jnp.minimum(hi, n_kv)
    else:
        hi = n_kv

    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(jk, carry):
        o, l, m = carry
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            kv_pos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            mask = kv_pos <= q_pos
            s = jnp.where(mask, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m, m_blk)
        # All-masked rows keep m_new == NEG_INF; clamp the reference point so
        # exp(NEG_INF - NEG_INF) can't poison l (same guard as blockwise).
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - safe_m))
        p = jnp.exp(s - safe_m)
        if causal:
            p = jnp.where(mask, p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o * corr + pv, l_new, m_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    o, l, m = jax.lax.fori_loop(0, hi, body, (o0, l0, m0))

    l_safe = jnp.maximum(l, 1e-20)
    o_ref[0] = (o / l_safe).astype(o_ref.dtype)
    # logsumexp residual for the backward pass: p = exp(s - lse).
    lse_ref[0] = (jnp.where(m == NEG_INF, NEG_INF, m) + jnp.log(l_safe))[:, 0]


def _flash_fwd_3d(q3, k3, v3, causal, block_q, block_k, interpret):
    """q3 [BH, T, D], k3/v3 [BKh, S, D] -> (o [BH, T, D] f32, lse [BH, T] f32).

    GQA rides the index maps: program b reads KV row b // n_rep, so shared KV
    heads are never copied n_rep× into HBM."""
    bh, t, d = q3.shape
    bkh, s_len, _ = k3.shape
    n_rep = bh // bkh
    scale = float(1.0 / (d ** 0.5))
    grid = (bh, t // block_q)
    kernel = functools.partial(
        _fwd_kernel, causal=causal, block_q=block_q, block_k=block_k,
        scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_len, d), lambda b, i, n=n_rep: (b // n, 0, 0)),
            pl.BlockSpec((1, s_len, d), lambda b, i, n=n_rep: (b // n, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3)


# ---------------------------------------------------------------------------
# backward
#
# Standard flash backward split: dq accumulates over KV blocks (same grid as
# the forward); dk/dv stream (repeat-head, q-block) pairs through an inner
# grid axis into a resident output tile — each (KV row, KV block) tile is
# owned by one grid column, so no cross-program races. delta = rowsum(do*o)
# is precomputed outside (one cheap fused elementwise reduce).


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   causal, block_q, block_k, scale):
    iq = pl.program_id(1)
    s_len = k_ref.shape[1]
    d = q_ref.shape[-1]
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, None]      # [bq, 1]
    delta = delta_ref[0][:, None]  # [bq, 1]

    n_kv = s_len // block_k
    if causal:
        hi = jnp.minimum((iq * block_q + block_q + block_k - 1) // block_k, n_kv)
    else:
        hi = n_kv
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(jk, dq):
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        # Fully-masked rows carry lse == NEG_INF (so s - lse would be +inf);
        # clamp the reference and zero p so their gradients stay 0, matching
        # the forward's guard.
        p = jnp.where(
            lse == NEG_INF, 0.0,
            jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse)),
        )
        if causal:
            kv_pos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            p = jnp.where(kv_pos <= q_pos, p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq_ref[0] = jax.lax.fori_loop(
        0, hi, body, jnp.zeros((block_q, d), jnp.float32)
    )


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                    dv_ref, *, causal, block_q, block_k, scale, n_q):
    """Grid (bkh, kv-block, n_rep·n_q): the innermost axis streams one
    (repeat-head, q-block) pair per step while the (b, j) output block stays
    resident in VMEM, accumulating across steps — VMEM holds one q block, not
    the repeat group's whole [n_rep, T, D] (which at llama-8k shapes would
    blow the budget)."""
    jk = pl.program_id(1)
    qi = pl.program_id(2)
    iq = jax.lax.rem(qi, n_q)  # q-block index within this repeat head

    @pl.when(qi == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    def contrib():
        q_blk = q_ref[0].astype(jnp.float32)   # [bq, D]
        do_blk = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, None]
        delta = delta_ref[0][:, None]
        k_blk = k_ref[0].astype(jnp.float32)   # [bk, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        # Same fully-masked-row guard as the dq pass: lse == NEG_INF rows
        # contribute nothing (not inf).
        p = jnp.where(
            lse == NEG_INF, 0.0,
            jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse)),
        )
        if causal:
            q_pos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kv_pos = jk * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            p = jnp.where(kv_pos <= q_pos, p, 0.0)
        dv_ref[0] = dv_ref[0] + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_ref[0] = dk_ref[0] + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # q blocks strictly before this KV block contribute nothing.
        pl.when(iq >= (jk * block_k) // block_q)(contrib)
    else:
        contrib()


def _flash_bwd_3d(q3, k3, v3, o3, lse, do3, causal, block_q, block_k,
                  interpret):
    bh, t, d = q3.shape
    bkh, s_len, _ = k3.shape
    n_rep = bh // bkh
    scale = float(1.0 / (d ** 0.5))
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, block_q=block_q,
                          block_k=block_k, scale=scale),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_len, d), lambda b, i, n=n_rep: (b // n, 0, 0)),
            pl.BlockSpec((1, s_len, d), lambda b, i, n=n_rep: (b // n, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)

    # One output block per (KV row, KV block); the innermost grid axis streams
    # the q-side repeat group one (repeat-head qi // n_q, q-block qi % n_q)
    # pair at a time (q3 rows for KV row b are the contiguous [b·n_rep,
    # (b+1)·n_rep)), accumulating into the resident dk/dv block.
    n_q = t // block_q
    q_map = lambda b, j, qi, n=n_rep, m=n_q: (b * n + qi // m, qi % m, 0)
    stat_map = lambda b, j, qi, n=n_rep, m=n_q: (b * n + qi // m, qi % m)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, block_q=block_q,
                          block_k=block_k, scale=scale, n_q=n_q),
        grid=(bkh, s_len // block_k, n_rep * n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_k, d), lambda b, j, qi: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, qi: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_q), stat_map),
            pl.BlockSpec((1, block_q), stat_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, qi: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, qi: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bkh, s_len, d), jnp.float32),
            jax.ShapeDtypeStruct((bkh, s_len, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper on the [BH, T, D] layout


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_3d(q3, k3, v3, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd_3d(q3, k3, v3, causal, block_q, block_k, interpret)
    return o


def _flash_3d_fwd(q3, k3, v3, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd_3d(q3, k3, v3, causal, block_q, block_k, interpret)
    return o, (q3, k3, v3, o, lse)


def _flash_3d_bwd(causal, block_q, block_k, interpret, res, do3):
    q3, k3, v3, o3, lse = res
    dq, dk, dv = _flash_bwd_3d(
        q3, k3, v3, o3, lse, do3, causal, block_q, block_k, interpret
    )
    return dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype)


_flash_3d.defvjp(_flash_3d_fwd, _flash_3d_bwd)


# ---------------------------------------------------------------------------
# public entry points (attention.py layout: [B, T, H, D])


def flash_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Kh, D]
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused flash attention; returns fp32 [B, T, H, D] (the blockwise
    contract). Raises ValueError when the sequence lengths admit no block
    size — dispatchers that want a silent fallback must check
    ``pick_flash_block`` first."""
    b, t, h, d = q.shape
    s_len, kh = k.shape[1], k.shape[2]
    bq, bk = block_q, block_k
    if bq is None or bk is None:
        # Autotune cache first (winners from kernels/autotune.py, keyed per
        # chip generation), then the largest-divisor heuristic; a stale entry
        # that doesn't divide THESE lengths is ignored, never an error.
        from dstack_tpu.workloads.kernels import autotune

        tuned = autotune.lookup("flash", d, max(t, s_len))
        if tuned is not None:
            if bq is None and t % tuned[0] == 0:
                bq = tuned[0]
            if bk is None and s_len % tuned[1] == 0:
                bk = tuned[1]
        bq = bq or pick_flash_block(t)
        bk = bk or pick_flash_block(s_len)
    if bq is None or bk is None or t % bq or s_len % bk:
        raise ValueError(
            f"flash attention needs block-divisible sequence lengths; "
            f"T={t} S={s_len} have no usable block (pass attn_impl=xla "
            f"or pad the sequence)"
        )
    # GQA: q rows for one KV head are adjacent after the reshape (q3 row
    # b·h + g·n_rep + r floors to KV row b·kh + g under // n_rep), so the
    # kernels index the shared KV row directly — no repeated copies.
    q3 = q.swapaxes(1, 2).reshape(b * h, t, d)
    k3 = k.swapaxes(1, 2).reshape(b * kh, s_len, d)
    v3 = v.swapaxes(1, 2).reshape(b * kh, s_len, d)
    o3 = _flash_3d(q3, k3, v3, causal, bq, bk, _use_interpret(interpret))
    return o3.reshape(b, h, t, d).swapaxes(1, 2)


def flash_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    interpret: Optional[bool] = None,
) -> jax.Array:
    """flash_attention under a (dp, fsdp, tp) mesh via shard_map.

    A Pallas custom call has no SPMD partitioning rule, so under a sharded jit
    it would force operand replication; attention is embarrassingly parallel
    over (batch, head), so shard_map over the batch axes and tp (heads) makes
    each shard run the kernel on its local [b_loc, T, h_loc, D] block. Requires
    sp == 1 (sequence-parallel runs use ring attention) and tp | n_kv_heads
    (each shard must keep whole GQA groups) — attention_core validates."""
    from jax.experimental.shard_map import shard_map

    spec = P(batch_axes, None, "tp", None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False,
    )
    def _local(q_loc, k_loc, v_loc):
        return flash_attention(
            q_loc, k_loc, v_loc, causal=causal, interpret=interpret
        )

    return _local(q, k, v)
