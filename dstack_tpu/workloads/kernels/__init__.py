"""In-repo Pallas TPU kernels for the workload hot paths.

Every kernel here runs in two modes from the same source:

- **compiled** (Mosaic) on a real TPU — the MFU/latency win;
- **interpreted** (``interpret=True``) everywhere else — tier-1 CPU tests
  exercise the *exact* kernel code, not a lookalike reference.

Modules:

- ``flash``      — block-tiled online-softmax flash attention, forward +
                   custom-VJP backward (training).
- ``splash``     — block-SPARSE flash attention: causal + local-window +
                   document masks become per-block loop bounds, so
                   fully-masked q/kv block pairs are never visited.
- ``paged``      — single-query paged-KV decode attention (serving).
- ``collective`` — collective matmuls: ``shard_map``-decomposed einsums that
                   interleave partial matmuls with ``ppermute`` ring steps so
                   parallelism-induced ICI transfers hide under MXU compute
                   (TP reduce-scatter ring + FSDP all-gather ring).
- ``autotune``   — persisted (block_q, block_kv) winners per (kernel, chip
                   generation, head_dim, seq), consulted by flash/splash.
- ``platform``   — chip-generation detection and interpret-mode defaults.
"""

from dstack_tpu.workloads.kernels.collective import (
    allgather_matmul,
    collective_matmul,
)
from dstack_tpu.workloads.kernels.flash import (
    flash_attention,
    flash_attention_sharded,
    pick_flash_block,
)
from dstack_tpu.workloads.kernels.paged import (
    paged_chunk_attention_pallas,
    paged_decode_attention_pallas,
)
from dstack_tpu.workloads.kernels.splash import (
    splash_attention,
    splash_attention_sharded,
)

__all__ = [
    "allgather_matmul",
    "collective_matmul",
    "flash_attention",
    "flash_attention_sharded",
    "paged_chunk_attention_pallas",
    "paged_decode_attention_pallas",
    "pick_flash_block",
    "splash_attention",
    "splash_attention_sharded",
]
