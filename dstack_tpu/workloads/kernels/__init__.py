"""In-repo Pallas TPU kernels for the workload hot paths.

Every kernel here runs in two modes from the same source:

- **compiled** (Mosaic) on a real TPU — the MFU/latency win;
- **interpreted** (``interpret=True``) everywhere else — tier-1 CPU tests
  exercise the *exact* kernel code, not a lookalike reference.

Modules:

- ``flash``      — block-tiled online-softmax flash attention, forward +
                   custom-VJP backward (training).
- ``paged``      — single-query paged-KV decode attention (serving).
- ``collective`` — collective matmul: ``shard_map``-decomposed einsum that
                   interleaves partial matmuls with ``ppermute`` ring steps so
                   tensor-parallel ICI transfers hide under MXU compute.
"""

from dstack_tpu.workloads.kernels.collective import collective_matmul
from dstack_tpu.workloads.kernels.flash import (
    flash_attention,
    flash_attention_sharded,
    pick_flash_block,
)
from dstack_tpu.workloads.kernels.paged import (
    paged_chunk_attention_pallas,
    paged_decode_attention_pallas,
)

__all__ = [
    "collective_matmul",
    "flash_attention",
    "flash_attention_sharded",
    "paged_chunk_attention_pallas",
    "paged_decode_attention_pallas",
    "pick_flash_block",
]
