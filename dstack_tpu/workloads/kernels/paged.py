"""Pallas paged-decode attention: the serving engine's decode hot path.

The XLA reference (``attention.paged_decode_attention``) gathers every slot's
page table into a dense [S, P·page, Kh, D] tensor each step — on TPU that is a
full HBM materialization of the padded KV window per layer per token. This
kernel walks each slot's page list directly: pages stay in HBM, each one is
DMA'd into a VMEM scratch buffer exactly once, and the online softmax
accumulates per page, so the working set is two pages instead of the whole
padded window. Page ids and KV lengths ride the scalar-prefetch lane
(``PrefetchScalarGridSpec``) so the DMA addresses are known before the body
runs.

The page walk is **double-buffered**: two VMEM scratch slots per stream, and
the copy for page i+1 starts *before* the body waits on (and computes over)
page i, so the HBM->VMEM hop for the next page hides under the current page's
dot products instead of serializing copy-wait-compute per page (ROADMAP item
4's leftover). Semantics are untouched — the same pages land in the same
order; only the wait moves.

Semantics are identical to the XLA reference (tests assert token-identity
through the engine, preemption included): slots attend to their first
``kv_lens[s]`` positions; ``kv_lens == 0`` slots produce finite garbage the
engine discards, never NaN.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _page_dma(pages_ref, scratch, sems, page_id, buf):
    """The (re)constructible descriptor for one page's HBM->VMEM copy into
    scratch slot ``buf``. Pallas async copies are started and awaited through
    an identical descriptor, so the double-buffer loop rebuilds it on both
    sides of the overlap window."""
    return pltpu.make_async_copy(pages_ref.at[page_id], scratch.at[buf], sems.at[buf])


def _paged_kernel(page_table_ref, kv_lens_ref, q_ref, k_pages_ref,
                  v_pages_ref, o_ref, k_scratch, v_scratch, sems, *,
                  page: int, n_rep: int):
    """One program per decode slot. q [1, H, D]; k/v pages stay in HBM and are
    DMA'd per page into alternating scratch slots (copy for page i+1 in
    flight while page i computes); out [1, H, D] fp32."""
    slot = pl.program_id(0)
    kh, d = k_pages_ref.shape[2], k_pages_ref.shape[3]
    kv_len = kv_lens_ref[slot]
    n_pages = pl.cdiv(kv_len, page)

    q = q_ref[0].astype(jnp.float32).reshape(kh, n_rep, d)
    scale = 1.0 / (d ** 0.5)

    @pl.when(n_pages > 0)
    def _prime():  # stage page 0 into slot 0 before the walk begins
        pid0 = page_table_ref[slot, 0]
        _page_dma(k_pages_ref, k_scratch, sems.at[0], pid0, 0).start()
        _page_dma(v_pages_ref, v_scratch, sems.at[1], pid0, 0).start()

    def body(p_idx, carry):
        o, l, m = carry
        page_id = page_table_ref[slot, p_idx]
        buf = jax.lax.rem(p_idx, 2)

        @pl.when(p_idx + 1 < n_pages)
        def _start_next():  # overlap: page i+1's DMA rides under page i's math
            nxt = page_table_ref[slot, p_idx + 1]
            nbuf = jax.lax.rem(p_idx + 1, 2)
            _page_dma(k_pages_ref, k_scratch, sems.at[0], nxt, nbuf).start()
            _page_dma(v_pages_ref, v_scratch, sems.at[1], nxt, nbuf).start()

        _page_dma(k_pages_ref, k_scratch, sems.at[0], page_id, buf).wait()
        _page_dma(v_pages_ref, v_scratch, sems.at[1], page_id, buf).wait()
        k_blk = k_scratch[buf].astype(jnp.float32)  # [page, Kh, D]
        v_blk = v_scratch[buf].astype(jnp.float32)
        # s[kh, n_rep, page]: contract D per KV head group.
        s = jax.lax.dot_general(
            q, k_blk, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale
        pos = p_idx * page + jax.lax.broadcasted_iota(
            jnp.int32, (kh, n_rep, page), 2
        )
        valid = pos < kv_len
        s = jnp.where(valid, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - safe_m))
        prob = jnp.where(valid, jnp.exp(s - safe_m), 0.0)
        l_new = l * corr + jnp.sum(prob, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            prob, v_blk, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )  # [kh, n_rep, D]
        return o * corr + pv, l_new, m_new

    o0 = jnp.zeros((kh, n_rep, d), jnp.float32)
    l0 = jnp.zeros((kh, n_rep, 1), jnp.float32)
    m0 = jnp.full((kh, n_rep, 1), NEG_INF, jnp.float32)
    o, l, m = jax.lax.fori_loop(0, n_pages, body, (o0, l0, m0))
    # Inactive slots (kv_len == 0) never looped: l == 0 -> zeros, not NaN.
    # The XLA reference emits uniform weights over garbage instead; both are
    # finite and both rows are discarded by the engine.
    o_ref[0] = (o / jnp.maximum(l, 1e-20)).reshape(kh * n_rep, d)


from dstack_tpu.workloads.kernels.platform import use_interpret as _use_interpret


def paged_decode_attention_pallas(
    q: jax.Array,           # [S, H, D] — one query per decode slot
    k_pages: jax.Array,     # [N, page, Kh, D]
    v_pages: jax.Array,
    page_table: jax.Array,  # [S, P] int32 page ids
    kv_lens: jax.Array,     # [S] valid KV length per slot
    *,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in for ``attention.paged_decode_attention`` (fp32 [S, H, D])."""
    s, h, d = q.shape
    n, page, kh, _ = k_pages.shape
    n_rep = h // kh
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s,),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i, *_: (i, 0, 0)),
        scratch_shapes=[
            # Two slots per stream: page i computes while page i+1 copies.
            pltpu.VMEM((2, page, kh, d), k_pages.dtype),
            pltpu.VMEM((2, page, kh, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(_paged_kernel, page=page, n_rep=n_rep)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, h, d), jnp.float32),
        interpret=_use_interpret(interpret),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32), q,
      k_pages, v_pages)


def _paged_chunk_kernel(page_table_ref, kv_lens_ref, starts_ref, q_ref,
                        k_pages_ref, v_pages_ref, o_ref, k_scratch, v_scratch,
                        sems, *, page: int, n_rep: int, chunk: int):
    """One program per slot, C chunk queries at positions starts[s]..+C-1.
    q [1, C, H, D]; pages stay in HBM, DMA'd per page into alternating
    scratch slots (same double-buffered walk as the decode kernel);
    out [1, C, H, D] fp32. Query i attends causally through its own position
    (its K/V already scattered into the pages), so the decode kernel above is
    the C == 1 special case of this accumulation."""
    slot = pl.program_id(0)
    kh, d = k_pages_ref.shape[2], k_pages_ref.shape[3]
    kv_len = kv_lens_ref[slot]
    start = starts_ref[slot]
    n_pages = pl.cdiv(kv_len, page)

    # [C, H, D] -> [kh, C*n_rep, d]: group rows by KV head so one dot_general
    # batches over kh (row r of the folded axis is chunk index r // n_rep).
    q = q_ref[0].astype(jnp.float32).reshape(chunk, kh, n_rep, d)
    q = q.transpose(1, 0, 2, 3).reshape(kh, chunk * n_rep, d)
    scale = 1.0 / (d ** 0.5)
    q_idx = jax.lax.broadcasted_iota(
        jnp.int32, (kh, chunk * n_rep, page), 1
    ) // n_rep

    @pl.when(n_pages > 0)
    def _prime():
        pid0 = page_table_ref[slot, 0]
        _page_dma(k_pages_ref, k_scratch, sems.at[0], pid0, 0).start()
        _page_dma(v_pages_ref, v_scratch, sems.at[1], pid0, 0).start()

    def body(p_idx, carry):
        o, l, m = carry
        page_id = page_table_ref[slot, p_idx]
        buf = jax.lax.rem(p_idx, 2)

        @pl.when(p_idx + 1 < n_pages)
        def _start_next():
            nxt = page_table_ref[slot, p_idx + 1]
            nbuf = jax.lax.rem(p_idx + 1, 2)
            _page_dma(k_pages_ref, k_scratch, sems.at[0], nxt, nbuf).start()
            _page_dma(v_pages_ref, v_scratch, sems.at[1], nxt, nbuf).start()

        _page_dma(k_pages_ref, k_scratch, sems.at[0], page_id, buf).wait()
        _page_dma(v_pages_ref, v_scratch, sems.at[1], page_id, buf).wait()
        k_blk = k_scratch[buf].astype(jnp.float32)  # [page, Kh, D]
        v_blk = v_scratch[buf].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        ) * scale  # [kh, C*n_rep, page]
        pos = p_idx * page + jax.lax.broadcasted_iota(
            jnp.int32, (kh, chunk * n_rep, page), 2
        )
        valid = (pos <= start + q_idx) & (pos < kv_len)
        s = jnp.where(valid, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - safe_m))
        prob = jnp.where(valid, jnp.exp(s - safe_m), 0.0)
        l_new = l * corr + jnp.sum(prob, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            prob, v_blk, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )  # [kh, C*n_rep, D]
        return o * corr + pv, l_new, m_new

    o0 = jnp.zeros((kh, chunk * n_rep, d), jnp.float32)
    l0 = jnp.zeros((kh, chunk * n_rep, 1), jnp.float32)
    m0 = jnp.full((kh, chunk * n_rep, 1), NEG_INF, jnp.float32)
    o, l, m = jax.lax.fori_loop(0, n_pages, body, (o0, l0, m0))
    o = o / jnp.maximum(l, 1e-20)
    o = o.reshape(kh, chunk, n_rep, d).transpose(1, 0, 2, 3)
    o_ref[0] = o.reshape(chunk, kh * n_rep, d)


def paged_chunk_attention_pallas(
    q: jax.Array,           # [S, C, H, D] — C chunk queries per slot
    k_pages: jax.Array,     # [N, page, Kh, D]
    v_pages: jax.Array,
    page_table: jax.Array,  # [S, P] int32 page ids
    starts: jax.Array,      # [S] absolute position of each slot's first query
    kv_lens: jax.Array,     # [S] total valid KV length (starts + chunk tokens)
    *,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Drop-in for ``attention.paged_chunk_attention`` (fp32 [S, C, H, D]) —
    the chunked-prefill / speculative-verify counterpart of the decode kernel:
    same page-at-a-time DMA walk, C queries sharing each page's single copy."""
    s, c, h, d = q.shape
    n, page, kh, _ = k_pages.shape
    n_rep = h // kh
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(s,),
        in_specs=[
            pl.BlockSpec((1, c, h, d), lambda i, *_: (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((1, c, h, d), lambda i, *_: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, page, kh, d), k_pages.dtype),
            pltpu.VMEM((2, page, kh, d), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
    )
    kernel = functools.partial(
        _paged_chunk_kernel, page=page, n_rep=n_rep, chunk=c
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s, c, h, d), jnp.float32),
        interpret=_use_interpret(interpret),
    )(page_table.astype(jnp.int32), kv_lens.astype(jnp.int32),
      starts.astype(jnp.int32), q, k_pages, v_pages)
