"""Autotuned (block_q, block_kv) tile sizes for the flash/splash kernels.

``pick_flash_block`` is a one-line heuristic (largest power-of-two divisor);
the REAL best tile depends on chip generation (VMEM size, MXU shape),
head_dim, and sequence length — the bench rounds showed 512-blocks beating
the public kernel's defaults ~6x on v5e forward, and there is no reason to
believe one size wins everywhere. This module closes the loop:

- ``tune()`` sweeps candidate (block_q, block_kv) pairs by timing the actual
  kernel (fwd+bwd, the train shape) and persists the winner;
- winners live in a JSON cache keyed ``kernel|generation|head_dim|seq`` —
  the generation is IN the key so a cache written on v5e can never poison a
  v5p job sharing the same filesystem;
- ``lookup()`` is consulted at trace time by ``flash_attention`` /
  ``splash_attention`` when the caller didn't pin blocks: cache file first,
  then shipped defaults (v5e/v5p, measured on the bench rounds), then the
  caller's heuristic. A corrupt or unwritable cache silently degrades to the
  shipped defaults — tuning is advisory, never load-bearing.

The cache directory defaults to ``~/.cache/dstack-tpu/autotune`` and is
overridable with ``DSTACK_TPU_AUTOTUNE_DIR`` (CI sandboxes, read-only
images, per-job scratch). Writes are atomic (tmp + rename) so concurrent
workers at worst lose a race, never corrupt the file.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional, Tuple

ENV_DIR = "DSTACK_TPU_AUTOTUNE_DIR"
CACHE_FILE = "blocks.json"

# Shipped per-generation winners from the dev-chip bench rounds (BASELINE.md):
# large blocks win on both v5e and v5p until head_dim=128 long-seq VMEM
# pressure caps v5e at 256-wide KV tiles. Entries are starting points — a
# local tune() overrides them via the cache file.
SHIPPED_DEFAULTS: Dict[str, Tuple[int, int]] = {}
for _kernel in ("flash", "splash"):
    for _seq in (1024, 2048, 4096, 8192):
        for _hd in (64, 128):
            SHIPPED_DEFAULTS[f"{_kernel}|v5p|{_hd}|{_seq}"] = (512, 512)
            SHIPPED_DEFAULTS[f"{_kernel}|v5e|{_hd}|{_seq}"] = (
                (512, 512) if _hd <= 64 or _seq <= 2048 else (512, 256)
            )

# (path, mtime) -> parsed cache, so trace-time lookups don't re-read the file.
_memo: Optional[Tuple[Tuple[str, float], Dict[str, Tuple[int, int]]]] = None


def cache_dir() -> str:
    return os.environ.get(ENV_DIR) or os.path.expanduser(
        "~/.cache/dstack-tpu/autotune"
    )


def cache_path() -> str:
    return os.path.join(cache_dir(), CACHE_FILE)


def _key(kernel: str, gen: str, head_dim: int, seq: int) -> str:
    return f"{kernel}|{gen}|{int(head_dim)}|{int(seq)}"


def _valid_blocks(v) -> Optional[Tuple[int, int]]:
    try:
        bq, bk = int(v[0]), int(v[1])
    except (TypeError, ValueError, IndexError):
        return None
    if bq <= 0 or bk <= 0 or len(v) != 2:
        return None
    return bq, bk


def _load_cache() -> Dict[str, Tuple[int, int]]:
    """Parsed cache file; {} on missing/corrupt (shipped defaults then win).
    Memoized on (path, mtime) so the per-trace cost is one stat call."""
    global _memo
    path = cache_path()
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return {}
    if _memo is not None and _memo[0] == (path, mtime):
        return _memo[1]
    entries: Dict[str, Tuple[int, int]] = {}
    try:
        with open(path) as f:
            raw = json.load(f)
        if isinstance(raw, dict):
            for k, v in raw.items():
                blocks = _valid_blocks(v)
                if blocks is not None:
                    entries[str(k)] = blocks
    except Exception:
        entries = {}
    _memo = ((path, mtime), entries)
    return entries


def lookup(
    kernel: str,
    head_dim: int,
    seq: int,
    gen: Optional[str] = None,
) -> Optional[Tuple[int, int]]:
    """Best-known (block_q, block_kv) for this kernel/chip/shape, or None
    (caller falls back to its heuristic). Tuned winners beat shipped
    defaults; the generation is part of the key on both layers."""
    if gen is None:
        from dstack_tpu.workloads.kernels.platform import chip_generation

        gen = chip_generation()
    key = _key(kernel, gen, head_dim, seq)
    cached = _load_cache().get(key)
    if cached is not None:
        return cached
    return SHIPPED_DEFAULTS.get(key)


def record(
    kernel: str,
    head_dim: int,
    seq: int,
    blocks: Tuple[int, int],
    gen: Optional[str] = None,
) -> bool:
    """Persist a tuned winner (atomic merge-write). Returns False instead of
    raising on any filesystem trouble — the cache is advisory."""
    global _memo
    if gen is None:
        from dstack_tpu.workloads.kernels.platform import chip_generation

        gen = chip_generation()
    blocks = _valid_blocks(blocks)
    if blocks is None:
        return False
    try:
        os.makedirs(cache_dir(), exist_ok=True)
        path = cache_path()
        entries = {k: list(v) for k, v in _load_cache().items()}
        entries[_key(kernel, gen, head_dim, seq)] = list(blocks)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(entries, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        _memo = None
        return True
    except OSError:
        return False


def candidate_blocks(seq_len: int, limit: int = 3) -> Tuple[int, ...]:
    """The largest ``limit`` power-of-two blocks dividing ``seq_len`` — the
    sweep space per side. Small blocks only matter for tiny test shapes."""
    from dstack_tpu.workloads.kernels.flash import _BLOCKS

    divs = tuple(b for b in _BLOCKS if seq_len >= b and seq_len % b == 0)
    return divs[:limit]


def tune(
    kernel: str,  # "flash" | "splash"
    q,
    k,
    v,
    *,
    causal: bool = True,
    window: int = 0,
    doc_ids=None,
    gen: Optional[str] = None,
    interpret: Optional[bool] = None,
    include_bwd: bool = True,
    repeats: int = 2,
    persist: bool = True,
) -> Dict:
    """Sweep (block_q, block_kv) candidates by timing the REAL kernel on the
    given operands (fwd+bwd by default — the train shape), persist the winner
    keyed (kernel, generation, head_dim, seq), and return the report:
    ``{"blocks": (bq, bk), "gen": ..., "sweep": {"bqxbk": seconds}}``.

    Runs OUTSIDE any trace (it times concrete executions) — call it once
    before compile, like the bench's "autotuned" variant or train.py's
    ``--autotune``."""
    import jax
    import jax.numpy as jnp

    from dstack_tpu.workloads.kernels import flash as flash_lib
    from dstack_tpu.workloads.kernels import splash as splash_lib

    if gen is None:
        from dstack_tpu.workloads.kernels.platform import chip_generation

        gen = chip_generation()
    t, d = q.shape[1], q.shape[3]
    s_len = k.shape[1]
    seq = max(t, s_len)

    def make_fn(bq, bk):
        def fwd(a, b, c):
            if kernel == "splash":
                return splash_lib.splash_attention(
                    a, b, c, causal=causal, window=window, doc_ids=doc_ids,
                    block_q=bq, block_k=bk, interpret=interpret,
                )
            return flash_lib.flash_attention(
                a, b, c, causal=causal, block_q=bq, block_k=bk,
                interpret=interpret,
            )

        if include_bwd:
            return jax.jit(jax.grad(lambda a, b, c: jnp.sum(fwd(a, b, c))))
        return jax.jit(fwd)

    sweep: Dict[str, float] = {}
    best: Optional[Tuple[int, int]] = None
    best_t = float("inf")
    for bq in candidate_blocks(t):
        for bk in candidate_blocks(s_len):
            fn = make_fn(bq, bk)
            try:
                jax.block_until_ready(fn(q, k, v))  # compile + warmup
                t0 = time.perf_counter()
                for _ in range(repeats):
                    jax.block_until_ready(fn(q, k, v))
                dt = (time.perf_counter() - t0) / repeats
            except Exception:
                continue
            sweep[f"{bq}x{bk}"] = dt
            if dt < best_t:
                best_t, best = dt, (bq, bk)
    report = {"kernel": kernel, "gen": gen, "head_dim": d, "seq": seq,
              "blocks": best, "sweep": sweep}
    if best is not None and persist:
        record(kernel, d, seq, best, gen=gen)
    return report
