"""Collective matmul: overlap parallelism-induced ICI transfers with compute.

Two rings, one idea (the TPU-concurrency paper's "move latency hiding into
the program"):

**TP reduce-scatter ring** (``collective_matmul``). The TP down-projections
(``wo``: [H·Dh, D], ``w_down``: [F, D]) contract a tp-sharded axis: XLA
computes the local partial matmul, then emits one big all-reduce the MXU
sits idle behind. The decomposition splits the local matmul into ``tp`` row
chunks and rides a ``ppermute`` ring:

  step s: send the accumulating chunk to the next device (async ICI hop),
          compute the next partial chunk (MXU),
          add the received accumulator.

After tp-1 steps each device owns one fully-reduced output chunk (a
reduce-scatter whose transfers hid under the partial matmuls), and one tiled
all-gather rebuilds the replicated activation.

**FSDP all-gather ring** (``allgather_matmul``). The column-parallel
up-projections (``wq``/``wk``/``wv``/``w_gate``/``w_up``: [D, N], D sharded
over (dp, fsdp)) are gathered ON USE under FSDP: XLA emits one monolithic
all-gather of the whole [D, N] weight before the matmul can start. The ring
form never materializes the gathered weight: each device walks the combined
(dp, fsdp) ring rotating WEIGHT shards (1/(dp·fsdp) of the tensor per
neighbor hop) while multiplying the matching K-slice of its local
activations — each hop's chunk matmul hides the next hop's transfer, and
peak weight memory stays one shard, not the full tensor.

Both are the same math as the XLA path — the 8-device CPU-mesh tests assert
equality to 1e-5, outputs and grads — but on TPU the per-step ppermute
overlaps with the next chunk's matmul under XLA's async collectives.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _default_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    # Master weights may be fp32 while activations are bf16: compute in the
    # activation dtype with fp32 accumulation, like every model projection.
    return jax.lax.dot_general(
        x, w.astype(x.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def can_overlap(
    mesh: Optional[Mesh],
    batch: int,
    seq: int,
    axis: str = "tp",
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
) -> bool:
    """True when the ring decomposition applies: tp > 1 and the LOCAL row
    count (batch and sequence after dp/fsdp/sp sharding) splits into tp
    chunks."""
    if mesh is None:
        return False
    tp = mesh.shape.get(axis, 1)
    if tp <= 1:
        return False
    data = 1
    for a in batch_axes:
        data *= mesh.shape.get(a, 1)
    sp = mesh.shape.get("sp", 1)
    if batch % data or seq % sp:
        return False
    rows = (batch // data) * (seq // sp)
    return rows % tp == 0


def collective_matmul(
    x: jax.Array,   # [B, T, K] — K sharded over `axis`
    w: jax.Array,   # [K, N]    — K sharded over `axis`
    mesh: Mesh,
    *,
    axis: str = "tp",
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    matmul: Optional[Callable[[jax.Array, jax.Array], jax.Array]] = None,
) -> jax.Array:
    """y = x @ w with the contraction axis sharded over ``axis`` on both
    operands; returns fp32 [B, T, N] replicated over ``axis`` (sharded over
    the batch axes / sp like any activation).

    ``matmul(x2d, w2d) -> f32`` computes each partial chunk — the default is a
    plain fp dot; pass the int8 path to quantize the partials (scales are
    per-shard, which is exactly per-channel on the local contraction rows).

    Caller contract: local rows (B/|batch_axes| · T/sp) divide tp — check with
    ``can_overlap`` and fall back to the plain einsum otherwise.
    """
    mm = matmul or _default_matmul
    tp = mesh.shape[axis]
    # One explicit gather for any other sharding on w's contraction dim (the
    # fsdp gather-on-use XLA inserts anyway); inside shard_map w is then
    # exactly [K/tp, N] per shard.
    w = jax.lax.with_sharding_constraint(w, NamedSharding(mesh, P(axis, None)))

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(batch_axes, "sp", axis), P(axis, None)),
        out_specs=P(batch_axes, "sp", None),
        check_rep=False,
    )
    def _ring(x_loc, w_loc):
        b, t, k = x_loc.shape
        n = w_loc.shape[1]
        rows = b * t
        chunk = rows // tp
        xf = x_loc.reshape(rows, k)
        my = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % tp) for i in range(tp)]

        def partial_chunk(c):
            xc = jax.lax.dynamic_slice_in_dim(xf, c * chunk, chunk, axis=0)
            return mm(xc, w_loc)  # [chunk, N] f32

        # Chunk c's accumulator starts at device c+1, rides the ring adding
        # each host's partial, and lands fully reduced on its owner c after
        # tp-1 hops. So device d seeds chunk d-1, and at step s it receives
        # the accumulator seeded s hops back — chunk d-s-1 — and adds its own
        # partial for that chunk.
        acc = partial_chunk((my - 1) % tp)

        def step(acc, s):
            acc = jax.lax.ppermute(acc, axis, perm)
            # ppermute does not depend on the next partial: XLA's async
            # collectives start the hop, the MXU fills it with this matmul.
            return acc + partial_chunk((my - s - 1) % tp), None

        if tp > 1:
            acc, _ = jax.lax.scan(step, acc, jnp.arange(1, tp))
        full = jax.lax.all_gather(acc, axis, axis=0, tiled=True)  # [rows, N]
        return full.reshape(b, t, n)

    return _ring(x, w)


def can_fsdp_overlap(
    mesh: Optional[Mesh],
    k_dim: int,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
) -> bool:
    """True when the all-gather ring decomposition applies to a column-
    parallel weight with contraction dim ``k_dim``: more than one device on
    the combined data axes, and ``k_dim`` splitting into whole shards."""
    if mesh is None:
        return False
    data = 1
    for a in batch_axes:
        data *= mesh.shape.get(a, 1)
    return data > 1 and k_dim % data == 0


def allgather_matmul(
    x: jax.Array,   # [B, T, K] — batch over (dp, fsdp), K replicated
    w: jax.Array,   # [K, N]    — K sharded over (dp, fsdp), N over tp
    mesh: Mesh,
    *,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    out_axis: str = "tp",
    matmul: Optional[Callable[[jax.Array, jax.Array], jax.Array]] = None,
) -> jax.Array:
    """y = x @ w for the FSDP column-parallel weights, with the gather-on-use
    all-gather decomposed into a weight-shard ring; returns fp32 [B, T, N]
    sharded like any activation (batch axes / sp / tp).

    Ring invariant: after ``s`` neighbor hops device ``my`` holds weight
    shard ``(my - s) % n`` (rows [(my-s)·K/n, (my-s+1)·K/n) of the full
    weight), which it multiplies with the SAME K-slice of its local
    activations — every device walks all ``n`` shards, so the sum over steps
    is exactly ``x @ w``, with each hop's transfer hiding under the previous
    chunk's matmul. Peak weight memory per device is one shard (1/n), not
    the materialized [K, N] the monolithic gather needs.

    ``matmul(x2d, w2d) -> f32`` computes each partial chunk (pass the
    int8/fp8 STE dot to quantize the partials — scales are per-chunk, which
    is per-channel on the chunk's contraction rows).

    Caller contract: ``can_fsdp_overlap(mesh, K)`` — d_model divides dp·fsdp
    and the data axes are non-trivial; fall back to the plain projection
    otherwise (config.validate_config raises loudly for CLI-requested
    combos)."""
    mm = matmul or _default_matmul
    sizes = [mesh.shape.get(a, 1) for a in batch_axes]
    n = 1
    for s in sizes:
        n *= s
    # One explicit reshard for any other layout on w (under train shardings
    # this is a no-op: PARAM_SPECS already puts K over (dp, fsdp)).
    w = jax.lax.with_sharding_constraint(
        w, NamedSharding(mesh, P(tuple(batch_axes), out_axis))
    )

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(tuple(batch_axes), "sp", None),
                  P(tuple(batch_axes), out_axis)),
        out_specs=P(tuple(batch_axes), "sp", out_axis),
        check_rep=False,
    )
    def _ring(x_loc, w_loc):
        b, t, k = x_loc.shape
        n_loc = w_loc.shape[1]
        kn = k // n
        xf = x_loc.reshape(b * t, k)
        # Combined row-major index over the data axes (matches how a
        # ppermute over the axis-name tuple orders the collapsed axis).
        my = jnp.zeros((), jnp.int32)
        for a, s in zip(batch_axes, sizes):
            my = my * s + jax.lax.axis_index(a)
        perm = [(i, (i + 1) % n) for i in range(n)]

        def partial_chunk(c, w_cur):
            xc = jax.lax.dynamic_slice_in_dim(xf, c * kn, kn, axis=1)
            return mm(xc, w_cur)  # [rows, n_loc] f32

        # Step 0 uses the resident shard (rows my·kn..); each subsequent hop
        # brings shard (my - s) % n.
        acc = partial_chunk(my, w_loc)

        def step(carry, s):
            acc, w_cur = carry
            w_cur = jax.lax.ppermute(w_cur, tuple(batch_axes), perm)
            acc = acc + partial_chunk((my - s) % n, w_cur)
            return (acc, w_cur), None

        if n > 1:
            (acc, _), _ = jax.lax.scan(step, (acc, w_loc), jnp.arange(1, n))
        return acc.reshape(b, t, n_loc)

    return _ring(x, w)
