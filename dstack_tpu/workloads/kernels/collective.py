"""Collective matmul: overlap tensor-parallel ICI transfers with compute.

The TP down-projections (``wo``: [H·Dh, D], ``w_down``: [F, D]) contract a
tp-sharded axis: XLA computes the local partial matmul, then emits one big
all-reduce the MXU sits idle behind. The collective-matmul decomposition (the
TPU-concurrency paper's "move latency hiding into the program") splits the
local matmul into ``tp`` row chunks and rides a ``ppermute`` ring:

  step s: send the accumulating chunk to the next device (async ICI hop),
          compute the next partial chunk (MXU),
          add the received accumulator.

After tp-1 steps each device owns one fully-reduced output chunk (a
reduce-scatter whose transfers hid under the partial matmuls), and one tiled
all-gather rebuilds the replicated activation. Same math as
matmul-then-all-reduce — the 8-device CPU-mesh test asserts equality — but on
TPU the per-step ppermute (1/tp of the tensor, neighbor hop) overlaps with the
next chunk's matmul under XLA's async collectives.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _default_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    # Master weights may be fp32 while activations are bf16: compute in the
    # activation dtype with fp32 accumulation, like every model projection.
    return jax.lax.dot_general(
        x, w.astype(x.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def can_overlap(
    mesh: Optional[Mesh],
    batch: int,
    seq: int,
    axis: str = "tp",
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
) -> bool:
    """True when the ring decomposition applies: tp > 1 and the LOCAL row
    count (batch and sequence after dp/fsdp/sp sharding) splits into tp
    chunks."""
    if mesh is None:
        return False
    tp = mesh.shape.get(axis, 1)
    if tp <= 1:
        return False
    data = 1
    for a in batch_axes:
        data *= mesh.shape.get(a, 1)
    sp = mesh.shape.get("sp", 1)
    if batch % data or seq % sp:
        return False
    rows = (batch // data) * (seq // sp)
    return rows % tp == 0


def collective_matmul(
    x: jax.Array,   # [B, T, K] — K sharded over `axis`
    w: jax.Array,   # [K, N]    — K sharded over `axis`
    mesh: Mesh,
    *,
    axis: str = "tp",
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    matmul: Optional[Callable[[jax.Array, jax.Array], jax.Array]] = None,
) -> jax.Array:
    """y = x @ w with the contraction axis sharded over ``axis`` on both
    operands; returns fp32 [B, T, N] replicated over ``axis`` (sharded over
    the batch axes / sp like any activation).

    ``matmul(x2d, w2d) -> f32`` computes each partial chunk — the default is a
    plain fp dot; pass the int8 path to quantize the partials (scales are
    per-shard, which is exactly per-channel on the local contraction rows).

    Caller contract: local rows (B/|batch_axes| · T/sp) divide tp — check with
    ``can_overlap`` and fall back to the plain einsum otherwise.
    """
    mm = matmul or _default_matmul
    tp = mesh.shape[axis]
    # One explicit gather for any other sharding on w's contraction dim (the
    # fsdp gather-on-use XLA inserts anyway); inside shard_map w is then
    # exactly [K/tp, N] per shard.
    w = jax.lax.with_sharding_constraint(w, NamedSharding(mesh, P(axis, None)))

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(batch_axes, "sp", axis), P(axis, None)),
        out_specs=P(batch_axes, "sp", None),
        check_rep=False,
    )
    def _ring(x_loc, w_loc):
        b, t, k = x_loc.shape
        n = w_loc.shape[1]
        rows = b * t
        chunk = rows // tp
        xf = x_loc.reshape(rows, k)
        my = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % tp) for i in range(tp)]

        def partial_chunk(c):
            xc = jax.lax.dynamic_slice_in_dim(xf, c * chunk, chunk, axis=0)
            return mm(xc, w_loc)  # [chunk, N] f32

        # Chunk c's accumulator starts at device c+1, rides the ring adding
        # each host's partial, and lands fully reduced on its owner c after
        # tp-1 hops. So device d seeds chunk d-1, and at step s it receives
        # the accumulator seeded s hops back — chunk d-s-1 — and adds its own
        # partial for that chunk.
        acc = partial_chunk((my - 1) % tp)

        def step(acc, s):
            acc = jax.lax.ppermute(acc, axis, perm)
            # ppermute does not depend on the next partial: XLA's async
            # collectives start the hop, the MXU fills it with this matmul.
            return acc + partial_chunk((my - s - 1) % tp), None

        if tp > 1:
            acc, _ = jax.lax.scan(step, acc, jnp.arange(1, tp))
        full = jax.lax.all_gather(acc, axis, axis=0, tiled=True)  # [rows, N]
        return full.reshape(b, t, n)

    return _ring(x, w)
