"""Splash attention: block-SPARSE flash — skip fully-masked q/kv block pairs.

The long-context half of ROADMAP item 4. ``kernels/flash.py`` already keeps
scores out of HBM, but a causal kernel still does T²/2 score work and a
local-window mask (the dominant long-context recipe) leaves most of that as
multiply-by-zero. This kernel turns the mask structure into LOOP BOUNDS:

- each (batch·head, q-block) program computes its live KV-block interval
  ``[lo, hi)`` from the causal frontier and the local window — blocks outside
  it are never read, so a window-W config does O(T·W) work instead of
  O(T²/2);
- the backward dk/dv grid applies the transposed bounds with ``pl.when``
  (q blocks outside a KV block's receptive band contribute nothing and skip
  their matmuls);
- document masks (``doc_ids [B, T]``: tokens attend only within their own
  document, the packed-sequence training layout) are data-dependent, so they
  stay ELEMENT masks inside live blocks — the online-softmax NEG_INF guard
  already handles rows whose every key is masked.

Layout, GQA handling, and the custom-VJP split (dq pass + resident-
accumulator dk/dv pass) mirror ``flash.py`` — one (batch·head, q-block)
program per grid cell on the [B·H, T, D] reshape, KV indexed at ``b //
n_rep`` so repeated heads never touch HBM. ``interpret=True`` runs the
identical code CPU-side; tier-1 tests assert fwd+grad parity against
``splash_reference`` (the masked materializing reference) to 1e-4.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as P

from dstack_tpu.workloads.kernels.flash import pick_flash_block
from dstack_tpu.workloads.kernels.platform import use_interpret as _use_interpret

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# reference (masked, materializing) — the parity target and the dispatcher's
# fallback for shapes the kernel can't tile.


def splash_reference(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Kh, D]
    v: jax.Array,
    *,
    causal: bool = True,
    window: int = 0,
    doc_ids: Optional[jax.Array] = None,  # [B, S] int32
) -> jax.Array:
    """Materialized attention under the splash mask (causal ∧ window ∧ same-
    document); returns fp32 [B, T, H, D]. O(T·S) memory — correctness
    reference and odd-shape fallback only."""
    b, t, h, d = q.shape
    s_len, kh = k.shape[1], k.shape[2]
    n_rep = h // kh
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    s = jnp.einsum("bthd,bshd->bhts", q, k,
                   preferred_element_type=jnp.float32) * scale
    q_pos = jnp.arange(t)[:, None]
    kv_pos = jnp.arange(s_len)[None, :]
    mask = jnp.ones((t, s_len), bool)
    if causal:
        mask &= kv_pos <= q_pos
    if window:
        mask &= kv_pos > q_pos - window
    mask = jnp.broadcast_to(mask[None], (b, t, s_len))
    if doc_ids is not None:
        mask = mask & (doc_ids[:, :t, None] == doc_ids[:, None, :s_len])
    s = jnp.where(mask[:, None], s, NEG_INF)
    # Rows with every key masked (leading positions of a window'd band, or a
    # one-token document) must come out zero, not NaN.
    any_live = jnp.any(mask, axis=-1)[:, None]  # [B, 1, T]
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(any_live[..., None], p, 0.0)
    return jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))


# ---------------------------------------------------------------------------
# live KV interval: the block-skipping arithmetic, shared by fwd and dq.


def _kv_bounds(iq, block_q, block_k, n_kv, causal, window):
    """[lo, hi) KV-block interval for q block ``iq``: causal bounds hi by the
    block's LAST query row, the window bounds lo by its FIRST. Both are exact
    — a block outside [lo, hi) has no unmasked element."""
    if causal:
        hi = jnp.minimum((iq * block_q + block_q + block_k - 1) // block_k,
                         n_kv)
    else:
        hi = n_kv
    if window:
        lo = jnp.maximum((iq * block_q - (window - 1)) // block_k, 0)
    else:
        lo = 0
    return lo, hi


def _element_mask(iq, jk, block_q, block_k, causal, window, docq, dock):
    """[bq, bk] bool mask inside one live block (None = nothing masked)."""
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    kv_pos = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = None
    if causal:
        mask = kv_pos <= q_pos
    if window:
        wmask = kv_pos > q_pos - window
        mask = wmask if mask is None else (mask & wmask)
    if docq is not None:
        dmask = docq[:, None] == dock[None, :]
        mask = dmask if mask is None else (mask & dmask)
    return mask


# ---------------------------------------------------------------------------
# forward


def _splash_fwd_kernel(q_ref, k_ref, v_ref, docq_ref, dock_ref, o_ref,
                       lse_ref, *, causal, window, has_docs, block_q, block_k,
                       scale):
    """One (batch·head, q-block) program. Refs: q [1, bq, D]; k/v [1, S, D];
    docq [1, bq]; dock [1, S]; o [1, bq, D]; lse [1, bq]."""
    iq = pl.program_id(1)
    s_len = k_ref.shape[1]
    d = q_ref.shape[-1]
    q = q_ref[0].astype(jnp.float32)
    docq = docq_ref[0] if has_docs else None

    n_kv = s_len // block_k
    lo, hi = _kv_bounds(iq, block_q, block_k, n_kv, causal, window)

    def body(jk, carry):
        o, l, m = carry
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        dock = (dock_ref[0, pl.ds(jk * block_k, block_k)]
                if has_docs else None)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        mask = _element_mask(iq, jk, block_q, block_k, causal, window, docq,
                             dock)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        # All-masked rows keep m_new == NEG_INF; clamp the reference point so
        # exp(NEG_INF - NEG_INF) can't poison l (same guard as flash).
        safe_m = jnp.where(m_new == NEG_INF, 0.0, m_new)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - safe_m))
        p = jnp.exp(s - safe_m)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return o * corr + pv, l_new, m_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    o, l, m = jax.lax.fori_loop(lo, hi, body, (o0, l0, m0))

    l_safe = jnp.maximum(l, 1e-20)
    o_ref[0] = (o / l_safe).astype(o_ref.dtype)
    lse_ref[0] = (jnp.where(m == NEG_INF, NEG_INF, m) + jnp.log(l_safe))[:, 0]


def _splash_fwd_3d(q3, k3, v3, docq2, dock2, causal, window, has_docs,
                   block_q, block_k, interpret):
    """q3 [BH, T, D], k3/v3 [BKh, S, D], docq2/dock2 [B, T]/[B, S] ->
    (o [BH, T, D] f32, lse [BH, T] f32)."""
    bh, t, d = q3.shape
    bkh, s_len, _ = k3.shape
    n_rep = bh // bkh
    h = bh // docq2.shape[0]
    scale = float(1.0 / (d ** 0.5))
    grid = (bh, t // block_q)
    kernel = functools.partial(
        _splash_fwd_kernel, causal=causal, window=window, has_docs=has_docs,
        block_q=block_q, block_k=block_k, scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_len, d), lambda b, i, n=n_rep: (b // n, 0, 0)),
            pl.BlockSpec((1, s_len, d), lambda b, i, n=n_rep: (b // n, 0, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, m=h: (b // m, i)),
            pl.BlockSpec((1, s_len), lambda b, i, m=h: (b // m, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, t), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, docq2, dock2)


# ---------------------------------------------------------------------------
# backward: dq accumulates over the same live KV interval; dk/dv stream the
# transposed band of q blocks into a resident accumulator (flash.py's grid),
# with pl.when skipping q blocks outside the KV block's receptive band.


def _splash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          docq_ref, dock_ref, dq_ref, *, causal, window,
                          has_docs, block_q, block_k, scale):
    iq = pl.program_id(1)
    s_len = k_ref.shape[1]
    d = q_ref.shape[-1]
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, None]
    delta = delta_ref[0][:, None]
    docq = docq_ref[0] if has_docs else None

    n_kv = s_len // block_k
    lo, hi = _kv_bounds(iq, block_q, block_k, n_kv, causal, window)

    def body(jk, dq):
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(jnp.float32)
        dock = (dock_ref[0, pl.ds(jk * block_k, block_k)]
                if has_docs else None)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        # Fully-masked rows carry lse == NEG_INF; clamp the reference and
        # zero p so their gradients stay 0 (flash.py's guard).
        p = jnp.where(
            lse == NEG_INF, 0.0,
            jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse)),
        )
        mask = _element_mask(iq, jk, block_q, block_k, causal, window, docq,
                             dock)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq_ref[0] = jax.lax.fori_loop(
        lo, hi, body, jnp.zeros((block_q, d), jnp.float32)
    )


def _splash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           docq_ref, dock_ref, dk_ref, dv_ref, *, causal,
                           window, has_docs, block_q, block_k, scale, n_q):
    """Grid (bkh, kv-block, n_rep·n_q): the (b, j) output block stays resident
    while the innermost axis streams (repeat-head, q-block) pairs; pairs
    outside the block's receptive band skip their matmuls entirely — the
    backward-pass face of the same block sparsity."""
    jk = pl.program_id(1)
    qi = pl.program_id(2)
    iq = jax.lax.rem(qi, n_q)

    @pl.when(qi == 0)
    def _init():
        dk_ref[...] = jnp.zeros_like(dk_ref)
        dv_ref[...] = jnp.zeros_like(dv_ref)

    def contrib():
        q_blk = q_ref[0].astype(jnp.float32)
        do_blk = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, None]
        delta = delta_ref[0][:, None]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        docq = docq_ref[0] if has_docs else None
        dock = dock_ref[0] if has_docs else None
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        p = jnp.where(
            lse == NEG_INF, 0.0,
            jnp.exp(s - jnp.where(lse == NEG_INF, 0.0, lse)),
        )
        mask = _element_mask(iq, jk, block_q, block_k, causal, window, docq,
                             dock)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        dv_ref[0] = dv_ref[0] + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_ref[0] = dk_ref[0] + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # Transposed band: causal kills q blocks strictly before the KV block;
    # the window kills q blocks past the KV block's last reachable query
    # (kv_pos + window - 1).
    live = None
    if causal:
        live = iq >= (jk * block_k) // block_q
    if window:
        wlive = iq * block_q <= jk * block_k + block_k - 1 + window - 1
        live = wlive if live is None else (live & wlive)
    if live is None:
        contrib()
    else:
        pl.when(live)(contrib)


def _splash_bwd_3d(q3, k3, v3, o3, lse, do3, docq2, dock2, causal, window,
                   has_docs, block_q, block_k, interpret):
    bh, t, d = q3.shape
    bkh, s_len, _ = k3.shape
    n_rep = bh // bkh
    h = bh // docq2.shape[0]
    kh = bkh // docq2.shape[0]
    scale = float(1.0 / (d ** 0.5))
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32), axis=-1)

    dq = pl.pallas_call(
        functools.partial(_splash_bwd_dq_kernel, causal=causal, window=window,
                          has_docs=has_docs, block_q=block_q, block_k=block_k,
                          scale=scale),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, s_len, d), lambda b, i, n=n_rep: (b // n, 0, 0)),
            pl.BlockSpec((1, s_len, d), lambda b, i, n=n_rep: (b // n, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i, m=h: (b // m, i)),
            pl.BlockSpec((1, s_len), lambda b, i, m=h: (b // m, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta, docq2, dock2)

    n_q = t // block_q
    q_map = lambda b, j, qi, n=n_rep, m=n_q: (b * n + qi // m, qi % m, 0)
    stat_map = lambda b, j, qi, n=n_rep, m=n_q: (b * n + qi // m, qi % m)
    # doc rows follow the batch of the streamed q (b·n_rep + qi//n_q maps to
    # batch (b·n_rep + qi//n_q) // h) and of the resident KV block (b // kh).
    docq_map = lambda b, j, qi, n=n_rep, m=n_q, hh=h: (
        (b * n + qi // m) // hh, qi % m
    )
    dock_map = lambda b, j, qi, k=kh: (b // k, j)
    dk, dv = pl.pallas_call(
        functools.partial(_splash_bwd_dkv_kernel, causal=causal,
                          window=window, has_docs=has_docs, block_q=block_q,
                          block_k=block_k, scale=scale, n_q=n_q),
        grid=(bkh, s_len // block_k, n_rep * n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_k, d), lambda b, j, qi: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, qi: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_q), stat_map),
            pl.BlockSpec((1, block_q), stat_map),
            pl.BlockSpec((1, block_q), docq_map),
            pl.BlockSpec((1, block_k), dock_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, qi: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, qi: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bkh, s_len, d), jnp.float32),
            jax.ShapeDtypeStruct((bkh, s_len, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta, docq2, dock2)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper on the [BH, T, D] layout. The doc-id operands are
# integer data, not differentiable state — their cotangents are float0.


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _splash_3d(q3, k3, v3, docq2, dock2, causal, window, has_docs, block_q,
               block_k, interpret):
    o, _ = _splash_fwd_3d(q3, k3, v3, docq2, dock2, causal, window, has_docs,
                          block_q, block_k, interpret)
    return o


def _splash_3d_fwd(q3, k3, v3, docq2, dock2, causal, window, has_docs,
                   block_q, block_k, interpret):
    o, lse = _splash_fwd_3d(q3, k3, v3, docq2, dock2, causal, window,
                            has_docs, block_q, block_k, interpret)
    return o, (q3, k3, v3, o, lse, docq2, dock2)


def _splash_3d_bwd(causal, window, has_docs, block_q, block_k, interpret,
                   res, do3):
    q3, k3, v3, o3, lse, docq2, dock2 = res
    dq, dk, dv = _splash_bwd_3d(
        q3, k3, v3, o3, lse, do3, docq2, dock2, causal, window, has_docs,
        block_q, block_k, interpret
    )
    zero_doc = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return (dq.astype(q3.dtype), dk.astype(k3.dtype), dv.astype(v3.dtype),
            zero_doc(docq2), zero_doc(dock2))


_splash_3d.defvjp(_splash_3d_fwd, _splash_3d_bwd)


# ---------------------------------------------------------------------------
# public entry points (attention.py layout: [B, T, H, D])


def splash_attention(
    q: jax.Array,  # [B, T, H, D]
    k: jax.Array,  # [B, S, Kh, D]
    v: jax.Array,
    *,
    causal: bool = True,
    window: int = 0,
    doc_ids: Optional[jax.Array] = None,  # [B, S] int32
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Block-sparse flash attention; returns fp32 [B, T, H, D].

    ``window`` > 0 restricts each query to the last ``window`` positions
    (inclusive of itself) and SKIPS KV blocks outside the band; ``doc_ids``
    adds a same-document element mask. Raises ValueError when the sequence
    lengths admit no block size — dispatchers that want a silent fallback
    check ``pick_flash_block`` first (attention.attention_core degrades to
    ``splash_reference``)."""
    b, t, h, d = q.shape
    s_len, kh = k.shape[1], k.shape[2]
    if window and not causal:
        raise ValueError("splash window masks are causal bands; "
                         "window > 0 requires causal=True")
    bq, bk = block_q, block_k
    if bq is None or bk is None:
        # Autotune cache first (winners from tune(), keyed per generation),
        # then the heuristic; a stale entry that doesn't divide THESE lengths
        # is ignored, never an error.
        from dstack_tpu.workloads.kernels import autotune

        tuned = autotune.lookup("splash", d, max(t, s_len))
        if tuned is not None:
            if bq is None and t % tuned[0] == 0:
                bq = tuned[0]
            if bk is None and s_len % tuned[1] == 0:
                bk = tuned[1]
        bq = bq or pick_flash_block(t)
        bk = bk or pick_flash_block(s_len)
    if bq is None or bk is None or t % bq or s_len % bk:
        raise ValueError(
            f"splash attention needs block-divisible sequence lengths; "
            f"T={t} S={s_len} have no usable block (pass attn_impl=xla "
            f"or pad the sequence)"
        )
    q3 = q.swapaxes(1, 2).reshape(b * h, t, d)
    k3 = k.swapaxes(1, 2).reshape(b * kh, s_len, d)
    v3 = v.swapaxes(1, 2).reshape(b * kh, s_len, d)
    has_docs = doc_ids is not None
    if has_docs:
        docq2 = doc_ids[:, :t].astype(jnp.int32)
        dock2 = doc_ids[:, :s_len].astype(jnp.int32)
    else:
        # Uniform zeros: the has_docs=False kernels never read these, but the
        # operand shapes stay static for the custom VJP.
        docq2 = jnp.zeros((b, t), jnp.int32)
        dock2 = jnp.zeros((b, s_len), jnp.int32)
    o3 = _splash_3d(q3, k3, v3, docq2, dock2, causal, int(window), has_docs,
                    bq, bk, _use_interpret(interpret))
    return o3.reshape(b, h, t, d).swapaxes(1, 2)


def splash_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    window: int = 0,
    doc_ids: Optional[jax.Array] = None,
    batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
    interpret: Optional[bool] = None,
) -> jax.Array:
    """splash_attention under a (dp, fsdp, tp) mesh via shard_map — same
    contract as ``flash_attention_sharded`` (sp == 1, tp | n_kv_heads); the
    doc-id plane shards over the batch axes alongside q/k/v."""
    from jax.experimental.shard_map import shard_map

    spec = P(batch_axes, None, "tp", None)
    doc_spec = P(batch_axes, None)
    if doc_ids is None:
        doc_ids = jnp.zeros(k.shape[:2], jnp.int32)
        has_docs = False
    else:
        doc_ids = doc_ids.astype(jnp.int32)
        has_docs = True

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec, doc_spec),
        out_specs=spec, check_rep=False,
    )
    def _local(q_loc, k_loc, v_loc, doc_loc):
        return splash_attention(
            q_loc, k_loc, v_loc, causal=causal, window=window,
            doc_ids=doc_loc if has_docs else None, interpret=interpret,
        )

    return _local(q, k, v, doc_ids)
