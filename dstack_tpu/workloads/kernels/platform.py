"""One place that answers "will this computation land on a TPU?".

Tests pin ``jax_default_device`` to CPU while the axon TPU plugin still owns
``jax.devices()[0]``, so the default device wins when set — the same probe
``attention.flash_available`` uses.
"""

from __future__ import annotations

from typing import Optional

import jax


def is_tpu_default_device() -> bool:
    try:
        dev = jax.config.jax_default_device or jax.devices()[0]
        return getattr(dev, "platform", None) == "tpu"
    except Exception:
        return False


def use_interpret(interpret: Optional[bool]) -> bool:
    """Kernel default: compiled (Mosaic) on TPU, interpreted elsewhere so CPU
    tests run the exact kernel code."""
    if interpret is not None:
        return interpret
    return not is_tpu_default_device()
