"""One place that answers "will this computation land on a TPU?".

Tests pin ``jax_default_device`` to CPU while the axon TPU plugin still owns
``jax.devices()[0]``, so the default device wins when set — the same probe
``attention.flash_available`` uses.
"""

from __future__ import annotations

from typing import Optional

import jax


def is_tpu_default_device() -> bool:
    try:
        dev = jax.config.jax_default_device or jax.devices()[0]
        return getattr(dev, "platform", None) == "tpu"
    except Exception:
        return False


def use_interpret(interpret: Optional[bool]) -> bool:
    """Kernel default: compiled (Mosaic) on TPU, interpreted elsewhere so CPU
    tests run the exact kernel code."""
    if interpret is not None:
        return interpret
    return not is_tpu_default_device()


# Chip generations that ship native fp8 MXU paths (e4m3/e5m2). v4/v5e run
# fp8 storage but upcast in the MXU — no throughput win, so quant=fp8 is
# rejected there at validate_config time rather than silently degrading.
FP8_GENERATIONS = ("v5p", "v6e", "v6p")


def chip_generation(env: Optional[dict] = None) -> str:
    """Best-effort TPU generation: "v4" / "v5e" / "v5p" / "v6e" / ... , "cpu"
    when the computation lands off-TPU, "unknown" on an unrecognized TPU.

    Sources, in order: the TPU_ACCELERATOR_TYPE env the GCE/GKE TPU runtime
    sets ("v5p-16", "v5litepod-8", "v6e-8"), then the PJRT device kind
    ("TPU v5p", "TPU v5 lite"). Off-TPU the answer is "cpu" — the autotune
    cache key and the fp8 gate both branch on it."""
    import os
    import re

    src = env if env is not None else os.environ
    acc = str(src.get("TPU_ACCELERATOR_TYPE", ""))
    if acc:
        if acc.startswith("v5litepod"):
            return "v5e"
        m = re.match(r"(v\d+[a-z]*)", acc)
        if m:
            return m.group(1)
    if not is_tpu_default_device():
        return "cpu"
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return "unknown"
    if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
        return "v5e"
    m = re.search(r"v(\d+)\s*([a-z]*)", kind)
    if m:
        return f"v{m.group(1)}{m.group(2)}"
    return "unknown"


def supports_fp8(gen: Optional[str] = None) -> bool:
    """True when fp8 matmuls hit a native MXU path (v5p and newer), AND off-TPU
    — CPU interpret/test runs emulate the identical numerics, so tier-1 tests
    and the bench smoke exercise the fp8 code everywhere."""
    gen = gen or chip_generation()
    return gen in FP8_GENERATIONS or gen == "cpu"
