"""Training step: optax AdamW under jit with explicit in/out shardings.

The scaling-book recipe end-to-end: params live sharded (sharding.PARAM_SPECS),
batches arrive sharded over (dp, fsdp) x sp, the whole step is one jit with donated
state — XLA inserts the all-gathers/reduce-scatters/psums implied by the shardings."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads.config import LlamaConfig
from dstack_tpu.workloads.sharding import batch_sharding, param_sharding


@dataclasses.dataclass
class TrainState:
    params: Dict[str, jax.Array]
    opt_state: optax.OptState
    step: jax.Array


def make_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    mu_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """AdamW with global-norm clipping. `mu_dtype="bfloat16"` stores the first
    moment in bf16 — halves its HBM (the variance and master params stay fp32),
    which is what buys the larger per-chip batch in bench.py."""
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def init_train_state(
    cfg: LlamaConfig,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
) -> TrainState:
    params = model_lib.init_params(cfg, key)
    if mesh is not None:
        shardings = param_sharding(mesh)
        params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    opt_state = optimizer.init(params)
    return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))


def make_train_step(
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
):
    """Returns jitted (state, tokens, targets) -> (state, metrics)."""

    def step(state: TrainState, tokens: jax.Array, targets: jax.Array):
        loss, grads = jax.value_and_grad(model_lib.loss_fn)(
            state.params, tokens, targets, cfg, mesh
        )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": state.step + 1,
        }
        return TrainState(new_params, new_opt, state.step + 1), metrics

    donate = (0,)
    if mesh is None:
        return jax.jit(step, donate_argnums=donate)
    bspec = batch_sharding(mesh)
    return jax.jit(
        step,
        donate_argnums=donate,
        in_shardings=(None, bspec, bspec),  # state shardings inferred from its arrays
    )


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step), None),
    lambda _, kids: TrainState(*kids),
)


def _timed_loop(steps: int, batch: int, seq: int, do_step,
                flops_per_step: float = 0.0) -> None:
    """Shared throughput loop: `do_step()` advances state and returns loss."""
    import time

    t0 = time.time()
    for i in range(steps):
        loss = do_step()
        if i == 0 or (i + 1) % 10 == 0:
            jax.block_until_ready(loss)
            dt = time.time() - t0
            steps_done = 1 if i == 0 else 10
            tok_s = steps_done * batch * seq / max(dt, 1e-9)
            tf = (f" {steps_done * flops_per_step / max(dt, 1e-9) / 1e12:.1f} TF/s"
                  if flops_per_step else "")
            print(f"step {i + 1}/{steps} loss={float(loss):.4f} "
                  f"{tok_s:,.0f} tok/s{tf}", flush=True)
            t0 = time.time()
    print("training done", flush=True)


def _moe_main(args, moe_lib) -> None:
    """MoE training entrypoint branch: experts over ep, the rest on dp."""
    import math

    if args.multislice:
        raise SystemExit("--multislice is not supported for MoE configs yet")
    devices = jax.devices()
    n = len(devices)
    cfg = moe_lib.MOE_PRESETS[args.config]
    # ep must divide both the device count and the expert count; the default
    # is the largest such axis (gcd), degrading to pure dp on odd fits.
    ep = args.ep or math.gcd(n, cfg.n_experts)
    if n % ep != 0:
        raise SystemExit(f"{n} devices not divisible by ep={ep}")
    if cfg.n_experts % ep != 0:
        raise SystemExit(
            f"n_experts={cfg.n_experts} not divisible by ep={ep};"
            f" pick --ep from the divisors of both"
        )
    mesh = moe_lib.make_moe_mesh(dp=n // ep, fsdp=1, ep=ep, tp=1, sp=1,
                                 devices=devices)
    data_shards = mesh.shape["dp"] * mesh.shape["fsdp"] * mesh.shape["ep"]
    batch = args.batch or 2 * data_shards
    seq = args.seq or cfg.max_seq_len
    print(f"config={args.config} devices={n} mesh={dict(mesh.shape)} "
          f"experts={cfg.n_experts} top_k={cfg.top_k} batch={batch} seq={seq}",
          flush=True)
    optimizer = make_optimizer()
    with mesh:
        params = moe_lib.shard_moe_params(
            moe_lib.init_moe_params(cfg, jax.random.PRNGKey(0)), mesh
        )
        opt_state = optimizer.init(params)
        step_fn = moe_lib.make_moe_train_step(cfg, optimizer, mesh)
        bspec = jax.sharding.NamedSharding(mesh, moe_lib.MOE_BATCH)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                               cfg.vocab_size),
            bspec,
        )
        state = {"params": params, "opt": opt_state}

        def do_step():
            state["params"], state["opt"], loss = step_fn(
                state["params"], state["opt"], tokens, tokens
            )
            return loss

        _timed_loop(args.steps, batch, seq, do_step)


def main() -> None:
    """`python -m dstack_tpu.workloads.train` — the runnable training entrypoint
    the example configurations submit (examples/*.dstack.yml). Synthetic data;
    prints per-step throughput and MFU so `dstack-tpu logs` shows live numbers."""
    import argparse

    from dstack_tpu.workloads.config import PRESETS, get_config
    from dstack_tpu.workloads.sharding import make_mesh, make_multislice_mesh

    from dstack_tpu.workloads import moe as moe_lib

    parser = argparse.ArgumentParser(prog="dstack_tpu.workloads.train")
    parser.add_argument("--config", default="test",
                        choices=sorted(PRESETS) + sorted(moe_lib.MOE_PRESETS))
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=0, help="global batch (0 = 2 per data shard)")
    parser.add_argument("--seq", type=int, default=0, help="sequence length (0 = config max)")
    parser.add_argument("--multislice", action="store_true",
                        help="leading dp axis over the MEGASCALE slice count")
    parser.add_argument("--ep", type=int, default=0,
                        help="expert-parallel axis size for MoE configs"
                             " (0 = largest ep dividing both the device count"
                             " and n_experts, i.e. their gcd)")
    args = parser.parse_args()

    if args.config in moe_lib.MOE_PRESETS:
        _moe_main(args, moe_lib)
        return

    cfg = get_config(args.config)
    devices = jax.devices()
    import os

    num_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "1"))
    if args.multislice and num_slices > 1:
        mesh = make_multislice_mesh(num_slices, devices=devices)
    else:
        mesh = make_mesh(devices=devices)  # all devices on fsdp
    data_shards = mesh.shape["dp"] * mesh.shape["fsdp"]
    batch = args.batch or 2 * data_shards
    seq = args.seq or cfg.max_seq_len

    print(f"config={args.config} devices={len(devices)} mesh={dict(mesh.shape)} "
          f"batch={batch} seq={seq}", flush=True)
    optimizer = make_optimizer()
    with mesh:
        state = init_train_state(cfg, jax.random.PRNGKey(0), optimizer, mesh)
        step_fn = make_train_step(cfg, optimizer, mesh)
        bspec = batch_sharding(mesh)
        key = jax.random.PRNGKey(1)
        tokens = jax.device_put(
            jax.random.randint(key, (batch, seq), 0, cfg.vocab_size), bspec
        )
        flops_per_step = cfg.flops_per_token(seq) * batch * seq
        box = {"state": state}

        def do_step():
            box["state"], metrics = step_fn(box["state"], tokens, tokens)
            return metrics["loss"]

        _timed_loop(args.steps, batch, seq, do_step, flops_per_step)


if __name__ == "__main__":
    main()
