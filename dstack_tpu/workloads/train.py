"""Training step: optax AdamW under jit with explicit in/out shardings.

The scaling-book recipe end-to-end: params live sharded (sharding.PARAM_SPECS),
batches arrive sharded over (dp, fsdp) x sp, the whole step is one jit with donated
state — XLA inserts the all-gathers/reduce-scatters/psums implied by the shardings."""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import telemetry as telemetry_lib
from dstack_tpu.workloads.config import LlamaConfig
from dstack_tpu.workloads.sharding import batch_sharding, param_sharding


@dataclasses.dataclass
class TrainState:
    params: Dict[str, jax.Array]
    opt_state: optax.OptState
    step: jax.Array


def make_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    mu_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """AdamW with global-norm clipping. `mu_dtype="bfloat16"` stores the first
    moment in bf16 — halves its HBM (the variance and master params stay fp32),
    which is what buys the larger per-chip batch in bench.py."""
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def init_train_state(
    cfg: LlamaConfig,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
) -> TrainState:
    params = model_lib.init_params(cfg, key)
    if mesh is not None:
        shardings = param_sharding(mesh)
        params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    opt_state = optimizer.init(params)
    return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))


def check_microbatch(batch: int, grad_accum: int, data_shards: int,
                     axes_label: str = "dp*fsdp") -> None:
    """Trace-time guard shared by the dense and MoE steps: a microbatch
    smaller than (or ragged over) the data-shard count silently reshards —
    some devices idle — which is a config error in a perf-tuned step, so fail
    loudly instead."""
    if grad_accum > 1 and (batch // grad_accum) % data_shards != 0:
        raise ValueError(
            f"microbatch {batch}//{grad_accum} must be a multiple of the "
            f"{data_shards} data shards ({axes_label}); grow --batch or "
            f"shrink --grad-accum"
        )


def accumulate_grads(loss_fn, params, tokens, targets, grad_accum: int,
                     micro_constraint=None, **loss_kwargs):
    """(mean_loss, mean_grads) over `grad_accum` microbatches via lax.scan.

    The batch's leading dim splits row-major into [A, B/A, T]; each scan step
    runs one microbatch's forward+backward and adds its grads into fp32
    accumulators (master-precision sums — bf16 accumulation drifts over long
    accumulation windows). Peak activation memory is ONE microbatch's, which
    is what lets a global batch grow ~A x without HBM blowup. Returned grads
    are cast back to each param's dtype for the optimizer."""
    b = tokens.shape[0]
    if b % grad_accum != 0:
        raise ValueError(f"batch {b} not divisible by grad_accum={grad_accum}")
    mb = b // grad_accum
    tok = tokens.reshape(grad_accum, mb, *tokens.shape[1:])
    tgt = targets.reshape(grad_accum, mb, *targets.shape[1:])
    if micro_constraint is not None:
        tok = micro_constraint(tok)
        tgt = micro_constraint(tgt)

    def micro(carry, xs):
        acc, loss_sum = carry
        t, g = xs
        loss, grads = jax.value_and_grad(loss_fn)(params, t, g, **loss_kwargs)
        acc = jax.tree.map(lambda a, gr: a + gr.astype(jnp.float32), acc, grads)
        return (acc, loss_sum + loss), None

    zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    (acc, loss_sum), _ = jax.lax.scan(micro, (zeros, jnp.zeros(())), (tok, tgt))
    grads = jax.tree.map(
        lambda a, p: (a / grad_accum).astype(p.dtype), acc, params
    )
    return loss_sum / grad_accum, grads


def make_train_step(
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    grad_accum: int = 1,
):
    """Returns jitted (state, tokens, targets) -> (state, metrics).

    `grad_accum=N` microbatches the global batch N ways (fp32 accumulators,
    one optimizer update per call); N=1 is the single-shot step. Donation and
    the explicit in/out shardings are identical either way."""
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    data_shards = mesh.shape["dp"] * mesh.shape["fsdp"] if mesh is not None else 1

    def micro_constraint(x):
        # Microbatches keep the batch sharding on their row dim: [A, B/A, T]
        # shards dim 1 over (dp, fsdp) and the sequence over sp, so each scan
        # step is exactly a smaller copy of the unaccumulated step.
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, ("dp", "fsdp"), "sp"))
        )

    def step(state: TrainState, tokens: jax.Array, targets: jax.Array):
        check_microbatch(tokens.shape[0], grad_accum, data_shards)
        if grad_accum == 1:
            loss, grads = jax.value_and_grad(model_lib.loss_fn)(
                state.params, tokens, targets, cfg, mesh
            )
        else:
            loss, grads = accumulate_grads(
                model_lib.loss_fn, state.params, tokens, targets, grad_accum,
                micro_constraint=micro_constraint, cfg=cfg, mesh=mesh,
            )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": state.step + 1,
        }
        return TrainState(new_params, new_opt, state.step + 1), metrics

    donate = (0,)
    if mesh is None:
        return jax.jit(step, donate_argnums=donate)
    bspec = batch_sharding(mesh)
    return jax.jit(
        step,
        donate_argnums=donate,
        in_shardings=(None, bspec, bspec),  # state shardings inferred from its arrays
    )


# Keyed registration: checkpoint manifests record leaf paths via keystr, and
# named fields (".params['embed']") are what lets a consumer restore a
# SUBTREE — the serve engine pulls just ".params" out of a train checkpoint
# (checkpoint.restore_subtree) without materializing the optimizer moments.
jax.tree_util.register_pytree_with_keys(
    TrainState,
    lambda s: (
        (
            (jax.tree_util.GetAttrKey("params"), s.params),
            (jax.tree_util.GetAttrKey("opt_state"), s.opt_state),
            (jax.tree_util.GetAttrKey("step"), s.step),
        ),
        None,
    ),
    lambda _, kids: TrainState(*kids),
)


@dataclasses.dataclass
class DraftTrainState:
    """Distillation state for the speculative-decode draft head (--draft-head):
    the FROZEN target rides along as ``params`` so one checkpoint is fully
    self-contained for serving — ``serve.load_serve_params`` restores the
    ``.params`` subtree and ``serve.load_draft_params`` the ``.draft`` subtree
    from the same step. Only ``draft`` trains; ``opt_state`` covers it alone."""

    params: Dict[str, jax.Array]
    draft: Dict[str, jax.Array]
    opt_state: optax.OptState
    step: jax.Array


jax.tree_util.register_pytree_with_keys(
    DraftTrainState,
    lambda s: (
        (
            (jax.tree_util.GetAttrKey("params"), s.params),
            (jax.tree_util.GetAttrKey("draft"), s.draft),
            (jax.tree_util.GetAttrKey("opt_state"), s.opt_state),
            (jax.tree_util.GetAttrKey("step"), s.step),
        ),
        None,
    ),
    lambda _, kids: DraftTrainState(*kids),
)


def make_draft_distill_step(
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
    rollout: int = 2,
):
    """Returns jitted (state: DraftTrainState, tokens) -> (state, loss).

    One distillation step: the frozen target's forward produces the hidden
    states and argmax labels, the head trains by cross-entropy against them
    (model.draft_distill_loss — gradients reach ``state.draft`` only; the
    target tree is a constant of the backward pass). No targets array: the
    teacher IS the label source, so the same batch stream train.py feeds the
    dense step drives distillation unchanged.

    Only the trained leaves (draft, opt_state, step) are donated. The frozen
    target is neither donated (the caller's params — a serve engine's, a
    bench's — must survive the step) nor returned through jit (which would
    copy the full target every step); the host-side wrapper threads the SAME
    params reference into the new state."""

    def inner(draft, opt_state, step_ct, params, tokens):
        loss, grads = jax.value_and_grad(
            lambda d: model_lib.draft_distill_loss(
                d, params, tokens, cfg, rollout=rollout, mesh=mesh
            )
        )(draft)
        updates, new_opt = optimizer.update(grads, opt_state, draft)
        return optax.apply_updates(draft, updates), new_opt, step_ct + 1, loss

    if mesh is None:
        jitted = jax.jit(inner, donate_argnums=(0, 1, 2))
    else:
        bspec = batch_sharding(mesh)
        jitted = jax.jit(
            inner, donate_argnums=(0, 1, 2),
            in_shardings=(None, None, None, None, bspec),
        )

    def step(state: DraftTrainState, tokens: jax.Array):
        new_draft, new_opt, new_step, loss = jitted(
            state.draft, state.opt_state, state.step, state.params, tokens
        )
        return DraftTrainState(state.params, new_draft, new_opt, new_step), loss

    return step


def _step_time_stats(times) -> Dict[str, float]:
    """p50/p90/mean seconds from a list of per-step wall times."""
    if not times:
        return {}
    s = sorted(times)
    pick = lambda q: s[min(len(s) - 1, int(q * len(s)))]  # noqa: E731
    return {
        "p50_s": pick(0.50),
        "p90_s": pick(0.90),
        "mean_s": sum(s) / len(s),
    }


def _device_peak_flops(device=None) -> float:
    """Public per-chip bf16 peak for MFU (same table bench.py cites); the
    fallback makes CPU-emitted "MFU" a tiny-but-honest fraction of a v5e."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind or "v5litepod" in kind:
        return 197e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind or "trillium" in kind:
        return 918e12
    if "v4" in kind:
        return 275e12
    return 197e12


def make_collective_fence(mesh: Optional[Mesh]):
    """A cheap timed all-reduce fence over the whole mesh, for per-step
    collective-wait attribution (the gang-health signal, ISSUE 15).

    The returned callable runs one scalar-sum over an array sharded across
    every mesh axis — XLA lowers it to a psum touching all devices — and
    returns its wall time. Called right after a step's ``block_until_ready``,
    the local devices are idle, so the fence measures how long this host
    waits for the REST of the gang: on a healthy pod it is the bare
    collective latency; when one host runs behind, every OTHER host's fence
    stretches by the lag (the straggler itself reports a near-zero fence and
    a long step — services/gang_health.py reads both sides). Compiled once
    here, outside the timed path. None when there is no mesh (nothing to
    fence)."""
    if mesh is None or mesh.size <= 1:
        return None
    axes = tuple(mesh.axis_names)
    x = jax.device_put(
        jnp.ones((mesh.size,), jnp.float32), NamedSharding(mesh, P(axes))
    )
    reduce = jax.jit(lambda a: a.sum())
    jax.block_until_ready(reduce(x))  # compile + first hop outside the loop

    def fence() -> float:
        t0 = time.perf_counter()
        jax.block_until_ready(reduce(x))
        return time.perf_counter() - t0

    return fence


def _timed_loop(steps: int, batch: int, seq: int, do_step,
                flops_per_step: float = 0.0, telemetry=None,
                step_extras=None, start_step: int = 0,
                on_step=None, fence=None) -> Dict[str, float]:
    """Shared throughput loop: `do_step()` advances state and returns loss.

    The first call is compile + first step and is reported (and returned) as
    `compile_s`, SEPARATE from the steady-state numbers — folding a 30 s
    compile into tok/s made short runs look slow and hid step-time jitter.
    Steady state reports the p50/p90 step-time distribution; throughput/MFU
    derive from p50 (the honest steady-state rate). The per-step sync this
    takes costs one host round trip (~10 ms) against multi-second training
    steps — <1%, and the prefetcher keeps transfers staged regardless.

    Every step also lands on the telemetry channel (workloads/telemetry.py,
    a no-op unless the runner agent exported DSTACK_TPU_TELEMETRY_PATH):
    compile_start/compile_end marks around the first call, then one `step`
    point per iteration — step_time, tok/s, TF/s, MFU against the chip's
    public peak, loss, plus whatever `step_extras()` returns (the entrypoints
    pass input-wait). This is what the server's goodput ledger is computed
    from, so the marks bracket exactly the non-productive time.

    ``start_step`` resumes numbering mid-run (a checkpoint restore): the loop
    performs ``steps - start_step`` iterations and steps are numbered
    ``start_step+1 .. steps`` in prints and telemetry, so a resumed run's
    step stream continues where the preempted one stopped. ``on_step(step,
    loss)`` fires after every completed step (the checkpoint hook; its
    exceptions propagate — an injected crash must actually kill the run).
    ``fence`` (make_collective_fence) runs after each step and its wall time
    lands on the step point as ``collective_wait_s`` — the cross-host wait
    signal gang-health skew attribution is built on."""
    if telemetry is None:
        telemetry = telemetry_lib.get_emitter()
    if steps - start_step <= 0:
        print(f"training done (0 steps remaining of {steps})", flush=True)
        return {}
    n_dev = jax.device_count()
    peak_flops = _device_peak_flops() * n_dev if flops_per_step else 0.0

    telemetry.mark("compile_start", steps=steps, batch=batch, seq=seq)
    t0 = time.perf_counter()
    loss = do_step()
    jax.block_until_ready(loss)
    compile_s = time.perf_counter() - t0
    telemetry.mark("compile_end", compile_s=compile_s)
    print(f"step {start_step + 1}/{steps} loss={float(loss):.4f} "
          f"compile+first-step {compile_s:.2f}s", flush=True)
    if on_step is not None:
        on_step(start_step + 1, loss)

    times = []
    for i in range(start_step + 1, steps):
        t0 = time.perf_counter()
        loss = do_step()
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0
        times.append(dt)
        point = {
            "loss": round(float(loss), 6),
            "tokens_per_sec": round(batch * seq / max(dt, 1e-9), 2),
        }
        if flops_per_step:
            fps = flops_per_step / max(dt, 1e-9)
            point["tf_per_sec"] = round(fps / 1e12, 3)
            point["mfu"] = round(fps / peak_flops, 5)
        if step_extras is not None:
            try:
                point.update(step_extras())
            except Exception:
                pass  # extras are advisory; never let them kill the loop
        if fence is not None:
            try:
                point["collective_wait_s"] = round(fence(), 6)
            except Exception:
                fence = None  # a broken fence degrades, never kills the loop
        telemetry.step(i + 1, round(dt, 6), **point)
        if on_step is not None:
            on_step(i + 1, loss)
        if (i + 1) % 10 == 0 or i == steps - 1:
            window = times[-10:]
            dt = sum(window) / len(window)
            tok_s = batch * seq / max(dt, 1e-9)
            tf = (f" {flops_per_step / max(dt, 1e-9) / 1e12:.1f} TF/s"
                  if flops_per_step else "")
            print(f"step {i + 1}/{steps} loss={float(loss):.4f} "
                  f"{tok_s:,.0f} tok/s{tf}", flush=True)

    stats = _step_time_stats(times)
    stats["compile_s"] = compile_s
    if times:
        p50 = stats["p50_s"]
        stats["tokens_per_sec"] = batch * seq / max(p50, 1e-9)
        summary = (f"done: {steps - start_step} steps, compile {compile_s:.2f}s, "
                   f"step p50 {p50 * 1000:.1f}ms p90 {stats['p90_s'] * 1000:.1f}ms, "
                   f"{stats['tokens_per_sec']:,.0f} tok/s")
        if flops_per_step:
            summary += f" {flops_per_step / max(p50, 1e-9) / 1e12:.1f} TF/s"
        print(summary, flush=True)
    else:
        print("training done", flush=True)
    telemetry.mark(
        "run_end",
        steps=steps,
        compile_s=round(compile_s, 4),
        tokens_per_sec=round(stats.get("tokens_per_sec", 0.0), 2),
        **{k: v for k, v in telemetry.stats().items() if k != "buffered"},
    )
    telemetry.flush()
    return stats


def make_checkpoint_manager(args, telemetry):
    """--checkpoint-dir -> a CheckpointManager (None when checkpointing is
    off). Import is lazy so the module stays importable without the flag."""
    if not getattr(args, "checkpoint_dir", ""):
        return None
    from dstack_tpu.workloads.checkpoint import CheckpointManager

    return CheckpointManager(args.checkpoint_dir, telemetry=telemetry)


def maybe_resume(manager, resume: bool, template, telemetry):
    """Restore the latest complete checkpoint into ``template`` when --resume
    is set. Returns (state, start_step). A fresh dir under --resume starts at
    step 0 (the first attempt of a retried gang passes the same flags)."""
    if manager is None or not resume:
        return template, 0
    step = manager.latest_step()
    if step is None:
        print("resume: no complete checkpoint found; starting fresh", flush=True)
        return template, 0
    state, manifest = manager.restore(template, step)
    start_step = int(manifest["step"])
    telemetry.mark(
        "restart", step=start_step, resumed=True,
        from_mesh=manifest.get("mesh"),
    )
    print(
        f"resumed from checkpoint step {start_step}"
        f" (saved on mesh {manifest.get('mesh')})",
        flush=True,
    )
    return state, start_step


def make_checkpoint_hook(manager, every: int, total_steps: int, get_state,
                         mesh_shape=None, resumed: bool = False):
    """The _timed_loop on_step hook: save every N steps (the final state is
    saved by the entrypoint after the loop, not here, so the last step isn't
    written twice). DSTACK_TPU_TRAIN_CRASH_AT_STEP injects a preemption for
    the smoke/bench harnesses — first attempt only (a resumed run sails past
    the crash step it already survived)."""
    import os

    crash_at = int(os.environ.get("DSTACK_TPU_TRAIN_CRASH_AT_STEP", "0") or 0)

    def on_step(step: int, loss) -> None:
        if manager is not None and every > 0 and step % every == 0 and step < total_steps:
            manager.save(step, get_state(), data_offset=step, mesh_shape=mesh_shape)
        if crash_at and not resumed and step >= crash_at:
            print(f"injected preemption: exiting at step {step}", flush=True)
            raise SystemExit(1)

    return on_step


def apply_perf_overrides(cfg, args):
    """--attn-impl / --quant / --tp-overlap / --fsdp-overlap / --attn-window
    -> config fields (shared by the dense and MoE CLI branches; empty flag =
    keep the config default)."""
    reps = {}
    if getattr(args, "attn_impl", ""):
        reps["attn_impl"] = args.attn_impl
    if getattr(args, "quant", ""):
        reps["quant"] = args.quant
    if getattr(args, "tp_overlap", False):
        reps["tp_overlap"] = True
    if getattr(args, "fsdp_overlap", False):
        reps["fsdp_overlap"] = True
    if getattr(args, "attn_window", 0):
        reps["attn_window"] = args.attn_window
    return dataclasses.replace(cfg, **reps) if reps else cfg


def _moe_main(args, moe_lib, data_lib) -> None:
    """MoE training entrypoint branch: experts over ep, the rest on dp."""
    import math

    from dstack_tpu.workloads.config import validate_config

    if args.multislice:
        raise SystemExit("--multislice is not supported for MoE configs yet")
    if args.tp > 1 or args.tp_overlap:
        # MoE meshes spend their devices on ep (per-expert matmuls never
        # contract a sharded axis) — silently ignoring the flags would break
        # the fail-loudly contract for explicitly requested perf levers.
        raise SystemExit(
            "--tp/--tp-overlap are not supported for MoE configs (the mesh "
            "is dp×ep; expert matmuls have no tp-sharded contraction to "
            "overlap) — drop the flags or pick a dense config"
        )
    devices = jax.devices()
    n = len(devices)
    cfg = moe_lib.MOE_PRESETS[args.config]
    if args.remat_policy:
        cfg = dataclasses.replace(cfg, remat=True, remat_policy=args.remat_policy)
    cfg = apply_perf_overrides(cfg, args)
    # ep must divide both the device count and the expert count; the default
    # is the largest such axis (gcd), degrading to pure dp on odd fits.
    ep = args.ep or math.gcd(n, cfg.n_experts)
    if n % ep != 0:
        raise SystemExit(f"{n} devices not divisible by ep={ep}")
    if cfg.n_experts % ep != 0:
        raise SystemExit(
            f"n_experts={cfg.n_experts} not divisible by ep={ep};"
            f" pick --ep from the divisors of both"
        )
    mesh = moe_lib.make_moe_mesh(dp=n // ep, fsdp=1, ep=ep, tp=1, sp=1,
                                 devices=devices)
    data_shards = mesh.shape["dp"] * mesh.shape["fsdp"] * mesh.shape["ep"]
    # Scale the default with accumulation: 2 rows per data shard per microbatch.
    batch = args.batch or 2 * data_shards * args.grad_accum
    seq = args.seq or cfg.max_seq_len
    validate_config(cfg, mesh, batch=batch // max(args.grad_accum, 1), seq=seq)
    print(f"config={args.config} devices={n} mesh={dict(mesh.shape)} "
          f"experts={cfg.n_experts} top_k={cfg.top_k} batch={batch} seq={seq} "
          f"grad_accum={args.grad_accum} prefetch={args.prefetch}",
          flush=True)
    telemetry = telemetry_lib.get_emitter()
    telemetry.set_identity(proc=jax.process_index())
    telemetry.mark("run_start", workload="train", config=args.config,
                   devices=n, batch=batch, seq=seq)
    optimizer = make_optimizer(mu_dtype=args.mu_dtype or None)
    ckpt = make_checkpoint_manager(args, telemetry)
    with mesh:
        params = moe_lib.shard_moe_params(
            moe_lib.init_moe_params(cfg, jax.random.PRNGKey(0)), mesh
        )
        opt_state = optimizer.init(params)
        state = {"params": params, "opt": opt_state}
        state, start_step = maybe_resume(ckpt, args.resume, state, telemetry)
        step_fn = moe_lib.make_moe_train_step(
            cfg, optimizer, mesh, grad_accum=args.grad_accum
        )
        feed = data_lib.input_pipeline(
            mesh, moe_lib.MOE_BATCH, batch, seq, cfg.vocab_size,
            data_path=args.data or None, prefetch=args.prefetch,
            start_batch=start_step,
        )
        feed_wait = {"s": 0.0}

        def do_step():
            t0 = time.perf_counter()
            tokens, targets = next(feed)
            feed_wait["s"] = time.perf_counter() - t0
            state["params"], state["opt"], loss = step_fn(
                state["params"], state["opt"], tokens, targets
            )
            return loss

        on_step = make_checkpoint_hook(
            ckpt, args.checkpoint_every, args.steps, lambda: state,
            mesh_shape=dict(mesh.shape), resumed=start_step > 0,
        )
        fence = make_collective_fence(mesh)
        try:
            _timed_loop(args.steps, batch, seq, do_step, telemetry=telemetry,
                        step_extras=lambda: {"input_wait_s": round(feed_wait["s"], 6)},
                        start_step=start_step, on_step=on_step, fence=fence)
            if ckpt is not None and args.checkpoint_every:
                ckpt.save(args.steps, state, data_offset=args.steps,
                          mesh_shape=dict(mesh.shape), block=True)
        finally:
            feed.close()
            if ckpt is not None:
                ckpt.close()
            telemetry.close()


def _draft_main(args, data_lib) -> None:
    """--draft-head: distill the speculative-decode draft head against the
    FROZEN target (model.draft_distill_loss). The target comes from the latest
    checkpoint in --checkpoint-dir when one exists (its ``.params`` subtree —
    a TrainState or an earlier DraftTrainState both restore) and synthetic
    init otherwise; the saved state is a DraftTrainState whose step numbers
    continue past the target's, so ``latest_step`` always lands on the
    draft-bearing checkpoint and serve can point --checkpoint-dir AND
    --spec-model at the same directory."""
    from dstack_tpu.workloads.config import get_config, validate_config
    from dstack_tpu.workloads.sharding import BATCH_SPEC, make_mesh

    cfg = get_config(args.config)
    cfg = apply_perf_overrides(cfg, args)
    devices = jax.devices()
    mesh = make_mesh(tp=args.tp, devices=devices)
    data_shards = mesh.shape["dp"] * mesh.shape["fsdp"]
    batch = args.batch or 2 * data_shards
    seq = args.seq or cfg.max_seq_len
    validate_config(cfg, mesh, batch=batch, seq=seq)
    print(f"draft-head distillation: config={args.config} devices={len(devices)} "
          f"mesh={dict(mesh.shape)} batch={batch} seq={seq} "
          f"layers={args.draft_layers} rollout={args.draft_rollout} "
          f"lr={args.draft_lr}", flush=True)
    telemetry = telemetry_lib.get_emitter()
    telemetry.set_identity(proc=jax.process_index())
    telemetry.mark("run_start", workload="train_draft", config=args.config,
                   devices=len(devices), batch=batch, seq=seq)
    optimizer = make_optimizer(learning_rate=args.draft_lr,
                               mu_dtype=args.mu_dtype or None)
    ckpt = make_checkpoint_manager(args, telemetry)

    def has_draft(step: int) -> bool:
        return any(
            leaf["key"].startswith(".draft")
            for leaf in ckpt.read_manifest(step)["leaves"]
        )

    with mesh:
        base_step = 0
        target = None
        resume_full = False
        latest = ckpt.latest_step() if ckpt is not None else None
        if latest is not None:
            if args.resume and has_draft(latest):
                resume_full = True  # continue a draft run in place
            else:
                shapes = jax.eval_shape(
                    lambda k: model_lib.init_params(cfg, k),
                    jax.random.PRNGKey(0),
                )
                shardings = param_sharding(mesh)
                template = {
                    k: jax.ShapeDtypeStruct(
                        v.shape, v.dtype, sharding=shardings.get(k)
                    )
                    for k, v in shapes.items()
                }
                target, manifest = ckpt.restore_subtree(
                    template, step=latest, prefix=".params"
                )
                base_step = int(manifest["step"])
                print(f"draft-head: frozen target from checkpoint step"
                      f" {base_step}", flush=True)
        if target is None and not resume_full:
            shardings = param_sharding(mesh)
            target = model_lib.init_params(cfg, jax.random.PRNGKey(0))
            target = {
                k: jax.device_put(v, shardings[k]) for k, v in target.items()
            }
        rep = NamedSharding(mesh, P())
        draft = jax.device_put(
            model_lib.init_draft_params(
                cfg, jax.random.PRNGKey(1), n_layers=args.draft_layers,
                d_ff=args.draft_ff,
            ),
            rep,
        )
        if resume_full:
            # Template with the CURRENT target shapes; restore() re-shards.
            target = jax.device_put(
                model_lib.init_params(cfg, jax.random.PRNGKey(0)),
                param_sharding(mesh),
            )
            state = DraftTrainState(
                target, draft, optimizer.init(draft),
                jnp.zeros((), jnp.int32),
            )
            state, manifest = ckpt.restore(state, latest)
            start_step = int(jax.device_get(state.step))
            base_step = int(manifest["step"]) - start_step
            print(f"resumed draft head at draft step {start_step}"
                  f" (checkpoint step {manifest['step']})", flush=True)
        else:
            state = DraftTrainState(
                target, draft, optimizer.init(draft),
                jnp.zeros((), jnp.int32),
            )
            start_step = 0
        step_fn = make_draft_distill_step(
            cfg, optimizer, mesh, rollout=args.draft_rollout
        )
        feed = data_lib.input_pipeline(
            mesh, BATCH_SPEC, batch, seq, cfg.vocab_size,
            data_path=args.data or None, prefetch=args.prefetch,
            start_batch=start_step,
        )
        box = {"state": state}
        feed_wait = {"s": 0.0}

        def do_step():
            t0 = time.perf_counter()
            tokens, _ = next(feed)  # the teacher labels itself; targets unused
            feed_wait["s"] = time.perf_counter() - t0
            box["state"], loss = step_fn(box["state"], tokens)
            return loss

        def on_step(step: int, loss) -> None:
            if (ckpt is not None and args.checkpoint_every
                    and step % args.checkpoint_every == 0
                    and step < args.steps):
                ckpt.save(base_step + step, box["state"], data_offset=step,
                          mesh_shape=dict(mesh.shape))

        try:
            _timed_loop(args.steps, batch, seq, do_step, telemetry=telemetry,
                        step_extras=lambda: {
                            "input_wait_s": round(feed_wait["s"], 6)
                        },
                        start_step=start_step, on_step=on_step)
            if ckpt is not None:
                ckpt.save(base_step + args.steps, box["state"],
                          data_offset=args.steps, mesh_shape=dict(mesh.shape),
                          block=True)
                print(f"draft head saved at checkpoint step"
                      f" {base_step + args.steps} (.draft subtree)",
                      flush=True)
        finally:
            feed.close()
            if ckpt is not None:
                ckpt.close()
            telemetry.close()


def main() -> None:
    """`python -m dstack_tpu.workloads.train` — the runnable training entrypoint
    the example configurations submit (examples/*.dstack.yml). Synthetic data by
    default (`--data tokens.bin` feeds a packed corpus); prints per-step
    throughput and MFU so `dstack-tpu logs` shows live numbers."""
    import argparse
    import dataclasses
    import os

    # Comm/compute-overlap XLA defaults BEFORE the first backend touch (XLA
    # parses XLA_FLAGS at client init). No-op unless PJRT_DEVICE=TPU — the
    # runner/docker contract — so CPU tests and dev chips are untouched.
    from dstack_tpu.workloads import xla_flags

    applied = xla_flags.apply()
    if applied:
        print(f"overlap XLA defaults applied: {applied['XLA_FLAGS']}", flush=True)

    from dstack_tpu.workloads import data as data_lib
    from dstack_tpu.workloads import moe as moe_lib
    from dstack_tpu.workloads.config import (
        ATTN_IMPLS,
        PRESETS,
        get_config,
        validate_config,
    )
    from dstack_tpu.workloads.sharding import BATCH_SPEC, make_mesh, make_multislice_mesh

    parser = argparse.ArgumentParser(prog="dstack_tpu.workloads.train")
    parser.add_argument("--config", default="test",
                        choices=sorted(PRESETS) + sorted(moe_lib.MOE_PRESETS))
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch", type=int, default=0, help="global batch (0 = 2 per data shard)")
    parser.add_argument("--seq", type=int, default=0, help="sequence length (0 = config max)")
    parser.add_argument("--multislice", action="store_true",
                        help="leading dp axis over the MEGASCALE slice count")
    parser.add_argument("--ep", type=int, default=0,
                        help="expert-parallel axis size for MoE configs"
                             " (0 = largest ep dividing both the device count"
                             " and n_experts, i.e. their gcd)")
    parser.add_argument("--grad-accum", type=int, default=1, dest="grad_accum",
                        help="microbatches per optimizer update (fp32 grad"
                             " accumulators; batch must divide evenly)")
    parser.add_argument("--mu-dtype", default="", dest="mu_dtype",
                        choices=["", "float32", "bfloat16"],
                        help="Adam first-moment dtype (bfloat16 halves its HBM)")
    parser.add_argument("--remat-policy", default="", dest="remat_policy",
                        choices=["", "full", "dots", "save_proj"],
                        help="rematerialization policy override (config default"
                             " if empty)")
    parser.add_argument("--attn-impl", default="", dest="attn_impl",
                        choices=[""] + list(ATTN_IMPLS),
                        help="attention core: auto (public Pallas kernel on a"
                             " meshless TPU, blockwise else), xla/blockwise,"
                             " flash (in-repo Pallas kernel; interpreted off-"
                             "TPU), flash_tpu, splash (block-sparse flash:"
                             " causal/local-window/document masks skip dead"
                             " blocks), plain (config default if empty)")
    parser.add_argument("--attn-window", type=int, default=0,
                        dest="attn_window",
                        help="local-attention window W for --attn-impl splash:"
                             " each query sees keys [i-W+1, i] (0 = dense"
                             " causal)")
    parser.add_argument("--quant", default="",
                        choices=["", "none", "int8", "fp8"],
                        help="matmul precision: int8/fp8 = dynamically-"
                             "quantized dots with fp32 accumulation and"
                             " straight-through gradients; fp8 (e4m3) needs a"
                             " v5p+ MXU (config default if empty)")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel axis size (fsdp absorbs the"
                             " rest); >1 is what makes --tp-overlap and the"
                             " sharded flash kernel's head split meaningful")
    parser.add_argument("--tp-overlap", action="store_true", dest="tp_overlap",
                        help="collective-matmul ring for the TP down-"
                             "projections: ICI transfers hide under partial"
                             " matmuls (requires --tp > 1)")
    parser.add_argument("--fsdp-overlap", action="store_true",
                        dest="fsdp_overlap",
                        help="all-gather ring for the FSDP column-parallel"
                             " up-projections (wq/wk/wv/w_gate/w_up): weight"
                             " shards rotate around dp*fsdp, each hop hiding"
                             " under the previous chunk's matmul (requires"
                             " dp*fsdp > 1 and d_model divisible by it)")
    parser.add_argument("--autotune", action="store_true",
                        help="sweep flash/splash (block_q, block_kv)"
                             " candidates for this (chip, head_dim, seq)"
                             " before training and persist the winner to the"
                             " autotune cache (kernels/autotune.py)")
    parser.add_argument("--prefetch", type=int, default=2,
                        help="input prefetch depth: batches staged to HBM ahead"
                             " of the step (0 = synchronous feed)")
    parser.add_argument("--data", default="",
                        help="flat binary token-id file (np.uint16) to train"
                             " on; empty = synthetic tokens")
    parser.add_argument("--checkpoint-every", type=int, default=0,
                        dest="checkpoint_every",
                        help="save an async distributed checkpoint every N"
                             " steps (0 = off; requires --checkpoint-dir)")
    parser.add_argument("--checkpoint-dir", default="", dest="checkpoint_dir",
                        help="directory for per-host checkpoint shards"
                             " (shared storage for multi-host restore)")
    parser.add_argument("--resume", action="store_true",
                        help="resume from the latest complete checkpoint in"
                             " --checkpoint-dir (elastic: the current mesh"
                             " may differ from the one that saved it); a"
                             " fresh dir starts at step 0")
    parser.add_argument("--draft-head", action="store_true", dest="draft_head",
                        help="distill a speculative-decode draft head against"
                             " the FROZEN target instead of training the"
                             " target: cross-entropy vs the target's argmax on"
                             " the same batch stream; saved as the .draft"
                             " subtree next to .params (serve --spec-model)")
    parser.add_argument("--draft-layers", type=int, default=2,
                        dest="draft_layers",
                        help="draft-head depth (pre-norm residual blocks)")
    parser.add_argument("--draft-ff", type=int, default=0, dest="draft_ff",
                        help="draft-head MLP width (0 = 2 * d_model)")
    parser.add_argument("--draft-lr", type=float, default=1e-3,
                        dest="draft_lr",
                        help="draft-head AdamW learning rate (the head is"
                             " small; it takes more than the target's 3e-4)")
    parser.add_argument("--draft-rollout", type=int, default=2,
                        dest="draft_rollout",
                        help="distillation rollout depth: steps >= 2 train the"
                             " head on its own continuations, which is what"
                             " later proposal positions see at serve time")
    args = parser.parse_args()
    if args.checkpoint_every and not args.checkpoint_dir:
        raise SystemExit("--checkpoint-every requires --checkpoint-dir")

    if args.config in moe_lib.MOE_PRESETS:
        if args.draft_head:
            raise SystemExit("--draft-head supports dense configs only")
        _moe_main(args, moe_lib, data_lib)
        return

    if args.draft_head:
        _draft_main(args, data_lib)
        return

    cfg = get_config(args.config)
    if args.remat_policy:
        cfg = dataclasses.replace(cfg, remat=True, remat_policy=args.remat_policy)
    cfg = apply_perf_overrides(cfg, args)
    devices = jax.devices()

    num_slices = int(os.environ.get("MEGASCALE_NUM_SLICES", "1"))
    if args.multislice and num_slices > 1:
        mesh = make_multislice_mesh(num_slices, tp=args.tp, devices=devices)
    else:
        # fsdp absorbs whatever --tp leaves (tp=1 -> all devices on fsdp).
        mesh = make_mesh(tp=args.tp, devices=devices)
    if args.tp_overlap and mesh.shape["tp"] <= 1:
        raise ValueError(
            "--tp-overlap needs a tensor-parallel mesh axis (pass --tp > 1);"
            " with tp=1 there is no all-reduce to hide and the ring is a"
            " silent no-op"
        )
    data_shards = mesh.shape["dp"] * mesh.shape["fsdp"]
    # The default batch scales with accumulation so each MICROBATCH keeps 2
    # rows per data shard (an explicit --batch must divide accordingly).
    batch = args.batch or 2 * data_shards * args.grad_accum
    seq = args.seq or cfg.max_seq_len
    # An explicitly requested invalid perf combo (flash + ring attention,
    # non-divisible blocks, a tp_overlap ring that can't split the batch)
    # must die HERE, before a multi-minute compile silently takes the slow
    # path.
    validate_config(cfg, mesh, batch=batch // max(args.grad_accum, 1), seq=seq)

    if args.autotune and cfg.attn_impl in ("flash", "splash"):
        # Sweep before the train compile so flash/splash pick up the tuned
        # (block_q, block_kv) for this exact (chip, head_dim, seq) — the
        # winner persists to the autotune cache, so later runs skip the sweep.
        from dstack_tpu.workloads.kernels import autotune as autotune_lib

        probe = jax.random.normal(
            jax.random.PRNGKey(0), (1, seq, 1, cfg.head_dim), jnp.float32
        )
        report = autotune_lib.tune(
            cfg.attn_impl, probe, probe, probe,
            causal=True, window=cfg.attn_window,
        )
        print(f"autotune: {report['kernel']} gen={report['gen']}"
              f" head_dim={report['head_dim']} seq={report['seq']}"
              f" -> blocks={report['blocks']}", flush=True)

    print(f"config={args.config} devices={len(devices)} mesh={dict(mesh.shape)} "
          f"batch={batch} seq={seq} grad_accum={args.grad_accum} "
          f"prefetch={args.prefetch}", flush=True)
    telemetry = telemetry_lib.get_emitter()
    # jax is up: refine the env-derived host identity with the authoritative
    # process index (multi-host gangs) so every point attributes per host.
    telemetry.set_identity(proc=jax.process_index())
    telemetry.mark("run_start", workload="train", config=args.config,
                   devices=len(devices), mesh=dict(mesh.shape), batch=batch,
                   seq=seq, grad_accum=args.grad_accum)
    optimizer = make_optimizer(mu_dtype=args.mu_dtype or None)
    ckpt = make_checkpoint_manager(args, telemetry)
    with mesh:
        state = init_train_state(cfg, jax.random.PRNGKey(0), optimizer, mesh)
        # Elastic restore: the template above is already sharded for THIS
        # mesh, so a checkpoint saved on a different topology re-shards here.
        state, start_step = maybe_resume(ckpt, args.resume, state, telemetry)
        step_fn = make_train_step(cfg, optimizer, mesh, grad_accum=args.grad_accum)
        feed = data_lib.input_pipeline(
            mesh, BATCH_SPEC, batch, seq, cfg.vocab_size,
            data_path=args.data or None, prefetch=args.prefetch,
            start_batch=start_step,
        )
        flops_per_step = cfg.flops_per_token(seq) * batch * seq
        box = {"state": state}
        feed_wait = {"s": 0.0}

        def do_step():
            t0 = time.perf_counter()
            tokens, targets = next(feed)
            feed_wait["s"] = time.perf_counter() - t0
            box["state"], metrics = step_fn(box["state"], tokens, targets)
            return metrics["loss"]

        on_step = make_checkpoint_hook(
            ckpt, args.checkpoint_every, args.steps,
            lambda: box["state"], mesh_shape=dict(mesh.shape),
            resumed=start_step > 0,
        )
        fence = make_collective_fence(mesh)
        try:
            _timed_loop(args.steps, batch, seq, do_step, flops_per_step,
                        telemetry=telemetry,
                        step_extras=lambda: {"input_wait_s": round(feed_wait["s"], 6)},
                        start_step=start_step, on_step=on_step, fence=fence)
            if ckpt is not None and args.checkpoint_every:
                # Final state: a completed run's last step is restorable too.
                ckpt.save(args.steps, box["state"], data_offset=args.steps,
                          mesh_shape=dict(mesh.shape), block=True)
        finally:
            feed.close()
            if ckpt is not None:
                ckpt.close()
            telemetry.close()


if __name__ == "__main__":
    main()
