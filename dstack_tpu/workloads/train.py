"""Training step: optax AdamW under jit with explicit in/out shardings.

The scaling-book recipe end-to-end: params live sharded (sharding.PARAM_SPECS),
batches arrive sharded over (dp, fsdp) x sp, the whole step is one jit with donated
state — XLA inserts the all-gathers/reduce-scatters/psums implied by the shardings."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads.config import LlamaConfig
from dstack_tpu.workloads.sharding import batch_sharding, param_sharding


@dataclasses.dataclass
class TrainState:
    params: Dict[str, jax.Array]
    opt_state: optax.OptState
    step: jax.Array


def make_optimizer(
    learning_rate: float = 3e-4,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
    mu_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """AdamW with global-norm clipping. `mu_dtype="bfloat16"` stores the first
    moment in bf16 — halves its HBM (the variance and master params stay fp32),
    which is what buys the larger per-chip batch in bench.py."""
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(learning_rate, b1=b1, b2=b2, weight_decay=weight_decay,
                    mu_dtype=mu_dtype),
    )


def init_train_state(
    cfg: LlamaConfig,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
) -> TrainState:
    params = model_lib.init_params(cfg, key)
    if mesh is not None:
        shardings = param_sharding(mesh)
        params = {k: jax.device_put(v, shardings[k]) for k, v in params.items()}
    opt_state = optimizer.init(params)
    return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))


def make_train_step(
    cfg: LlamaConfig,
    optimizer: optax.GradientTransformation,
    mesh: Optional[Mesh] = None,
):
    """Returns jitted (state, tokens, targets) -> (state, metrics)."""

    def step(state: TrainState, tokens: jax.Array, targets: jax.Array):
        loss, grads = jax.value_and_grad(model_lib.loss_fn)(
            state.params, tokens, targets, cfg, mesh
        )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": state.step + 1,
        }
        return TrainState(new_params, new_opt, state.step + 1), metrics

    donate = (0,)
    if mesh is None:
        return jax.jit(step, donate_argnums=donate)
    bspec = batch_sharding(mesh)
    return jax.jit(
        step,
        donate_argnums=donate,
        in_shardings=(None, bspec, bspec),  # state shardings inferred from its arrays
    )


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.params, s.opt_state, s.step), None),
    lambda _, kids: TrainState(*kids),
)
