"""Mesh + sharding rules for the Llama workload.

TPU-first design (scaling-book recipe): pick a mesh, annotate shardings with
NamedSharding, let XLA insert the collectives. Axes:

- ``dp``   data parallel (pure replication of params, batch split)
- ``fsdp`` fully-sharded data parallel (params sharded over it, batch split;
           XLA inserts all-gather on use / reduce-scatter on grads)
- ``tp``   tensor parallel (attention heads / MLP hidden sharded)
- ``sp``   sequence/context parallel (activations sharded over sequence; ring
           attention moves KV blocks around this axis over ICI)

Parity note: the reference has no model parallelism of its own (SURVEY §2.6); this is
the workload-side counterpart the TPU framework ships as a first-class example.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ("dp", "fsdp", "tp", "sp")


def make_mesh(
    dp: int = 1,
    fsdp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    devices=None,
) -> Mesh:
    """Build a (dp, fsdp, tp, sp) mesh; fsdp=None absorbs the remaining devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if fsdp is None:
        denom = dp * tp * sp
        if n % denom != 0:
            raise ValueError(f"{n} devices not divisible by dp*tp*sp={denom}")
        fsdp = n // denom
    if dp * fsdp * tp * sp != n:
        raise ValueError(f"mesh {dp}x{fsdp}x{tp}x{sp} != {n} devices")
    arr = np.array(devices).reshape(dp, fsdp, tp, sp)
    return Mesh(arr, MESH_AXES)


def make_multislice_mesh(
    num_slices: int,
    dp: int = 1,
    fsdp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    devices=None,
) -> Mesh:
    """Multislice mesh: the LEADING dp axis spans slices over DCN (MegaScale);
    everything inside stays on one slice's ICI.

    The scaling-book multislice recipe: only pure data parallelism crosses the
    slow DCN hop, so the device array is ordered slice-major (on TPU hardware,
    sorted by ``device.slice_index``) and the dp axis absorbs the slice count —
    XLA then emits the cross-slice gradient all-reduce over DCN and every other
    collective over ICI. Cluster env contract: MEGASCALE_NUM_SLICES/SLICE_ID
    (runner/src/executor.cpp cluster_env)."""
    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) % num_slices != 0:
        raise ValueError(f"{len(devices)} devices not divisible by {num_slices} slices")
    # Group slice-major so contiguous blocks of the leading axis are one slice.
    if getattr(devices[0], "slice_index", None) is not None:
        devices = sorted(devices, key=lambda d: (d.slice_index, d.id))
    return make_mesh(dp=num_slices * dp, fsdp=fsdp, tp=tp, sp=sp, devices=devices)


# Logical -> physical sharding rules for the stacked-layer parameter tree (model.py).
# Layer-stacked tensors carry a leading L axis that stays unsharded.
PARAM_SPECS: Dict[str, P] = {
    "embed": P("tp", ("dp", "fsdp")),          # [V, D] vocab over tp
    "wq": P(None, ("dp", "fsdp"), "tp"),       # [L, D, H*Dh]
    "wk": P(None, ("dp", "fsdp"), "tp"),       # [L, D, Hkv*Dh]
    "wv": P(None, ("dp", "fsdp"), "tp"),
    "wo": P(None, "tp", ("dp", "fsdp")),       # [L, H*Dh, D]
    "w_gate": P(None, ("dp", "fsdp"), "tp"),   # [L, D, F]
    "w_up": P(None, ("dp", "fsdp"), "tp"),
    "w_down": P(None, "tp", ("dp", "fsdp")),   # [L, F, D]
    "attn_norm": P(None, None),                # [L, D]
    "mlp_norm": P(None, None),
    "final_norm": P(None),                     # [D]
    "lm_head": P(("dp", "fsdp"), "tp"),        # [D, V]
}

# Note: params are sharded over BOTH dp and fsdp ("zero-3 over the dp axis too") —
# with dp=1 this degenerates to classic FSDP; replicated-dp is recovered by dp=1.

BATCH_SPEC = P(("dp", "fsdp"), "sp")  # tokens [B, T]
ACT_SPEC = P(("dp", "fsdp"), "sp", "tp")  # activations [B, T, D']


def param_sharding(mesh: Mesh) -> Dict[str, NamedSharding]:
    return {k: NamedSharding(mesh, spec) for k, spec in PARAM_SPECS.items()}


def shard_params(params: Dict[str, jax.Array], mesh: Mesh) -> Dict[str, jax.Array]:
    shardings = param_sharding(mesh)
    return {k: jax.device_put(v, shardings[k]) for k, v in params.items()}


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, BATCH_SPEC)


def pad_to_multiple(x: int, m: int) -> int:
    return int(math.ceil(x / m) * m)
