"""Mesh + sharding rules for the Llama workload.

TPU-first design (scaling-book recipe): pick a mesh, annotate shardings with
NamedSharding, let XLA insert the collectives. Axes:

- ``dp``   data parallel (pure replication of params, batch split)
- ``fsdp`` fully-sharded data parallel (params sharded over it, batch split;
           XLA inserts all-gather on use / reduce-scatter on grads)
- ``tp``   tensor parallel (attention heads / MLP hidden sharded)
- ``sp``   sequence/context parallel (activations sharded over sequence; ring
           attention moves KV blocks around this axis over ICI)

Parity note: the reference has no model parallelism of its own (SURVEY §2.6); this is
the workload-side counterpart the TPU framework ships as a first-class example.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MESH_AXES = ("dp", "fsdp", "tp", "sp")


def make_mesh(
    dp: int = 1,
    fsdp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    devices=None,
) -> Mesh:
    """Build a (dp, fsdp, tp, sp) mesh; fsdp=None absorbs the remaining devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if fsdp is None:
        denom = dp * tp * sp
        if n % denom != 0:
            raise ValueError(f"{n} devices not divisible by dp*tp*sp={denom}")
        fsdp = n // denom
    if dp * fsdp * tp * sp != n:
        raise ValueError(f"mesh {dp}x{fsdp}x{tp}x{sp} != {n} devices")
    arr = np.array(devices).reshape(dp, fsdp, tp, sp)
    return Mesh(arr, MESH_AXES)


def make_multislice_mesh(
    num_slices: int,
    dp: int = 1,
    fsdp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    devices=None,
) -> Mesh:
    """Multislice mesh: the LEADING dp axis spans slices over DCN (MegaScale);
    everything inside stays on one slice's ICI.

    The scaling-book multislice recipe: only pure data parallelism crosses the
    slow DCN hop, so the device array is ordered slice-major (on TPU hardware,
    sorted by ``device.slice_index``) and the dp axis absorbs the slice count —
    XLA then emits the cross-slice gradient all-reduce over DCN and every other
    collective over ICI. Cluster env contract: MEGASCALE_NUM_SLICES/SLICE_ID
    (runner/src/executor.cpp cluster_env)."""
    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) % num_slices != 0:
        raise ValueError(f"{len(devices)} devices not divisible by {num_slices} slices")
    # Group slice-major so contiguous blocks of the leading axis are one slice.
    if getattr(devices[0], "slice_index", None) is not None:
        devices = sorted(devices, key=lambda d: (d.slice_index, d.id))
    return make_mesh(dp=num_slices * dp, fsdp=fsdp, tp=tp, sp=sp, devices=devices)


# Logical -> physical sharding rules for the stacked-layer parameter tree (model.py).
# Layer-stacked tensors carry a leading L axis that stays unsharded.
PARAM_SPECS: Dict[str, P] = {
    "embed": P("tp", ("dp", "fsdp")),          # [V, D] vocab over tp
    "wq": P(None, ("dp", "fsdp"), "tp"),       # [L, D, H*Dh]
    "wk": P(None, ("dp", "fsdp"), "tp"),       # [L, D, Hkv*Dh]
    "wv": P(None, ("dp", "fsdp"), "tp"),
    "wo": P(None, "tp", ("dp", "fsdp")),       # [L, H*Dh, D]
    "w_gate": P(None, ("dp", "fsdp"), "tp"),   # [L, D, F]
    "w_up": P(None, ("dp", "fsdp"), "tp"),
    "w_down": P(None, "tp", ("dp", "fsdp")),   # [L, F, D]
    "attn_norm": P(None, None),                # [L, D]
    "mlp_norm": P(None, None),
    "final_norm": P(None),                     # [D]
    "lm_head": P(("dp", "fsdp"), "tp"),        # [D, V]
}

# Note: params are sharded over BOTH dp and fsdp ("zero-3 over the dp axis too") —
# with dp=1 this degenerates to classic FSDP; replicated-dp is recovered by dp=1.

BATCH_SPEC = P(("dp", "fsdp"), "sp")  # tokens [B, T]
ACT_SPEC = P(("dp", "fsdp"), "sp", "tp")  # activations [B, T, D']


def param_sharding(mesh: Mesh) -> Dict[str, NamedSharding]:
    return {k: NamedSharding(mesh, spec) for k, spec in PARAM_SPECS.items()}


# ---------------------------------------------------------------------------
# Serving mesh: one replica spans a multi-chip slice (the Gemma-31B shape —
# the model only fits sharded). Axes:
#
# - ``tp`` tensor parallel: attention/MLP projections and KV heads sharded —
#          the Megatron layout (column-parallel up/gate/QKV, row-parallel
#          down/wo with one all-reduce each), expressed as NamedShardings
#          for GSPMD rather than explicit collectives.
# - ``dd`` decode-data replica axis: pure replication (params AND the engine's
#          host-driven batches — every spec below simply omits it). It exists
#          so a serve mesh can absorb a whole slice (tp x dd = devices) and a
#          checkpoint restores onto it unchanged; scheduling stays host-side.

SERVE_MESH_AXES = ("dd", "tp")


def make_serve_mesh(tp: int = 1, dd: Optional[int] = None, devices=None) -> Mesh:
    """Build a (dd, tp) serving mesh; dd=None absorbs the remaining devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if dd is None:
        if n % tp != 0:
            raise ValueError(f"{n} devices not divisible by tp={tp}")
        dd = n // tp
    if dd * tp != n:
        raise ValueError(f"serve mesh {dd}x{tp} != {n} devices")
    arr = np.array(devices).reshape(dd, tp)
    return Mesh(arr, SERVE_MESH_AXES)


# Serve-side logical -> physical rules for the same stacked-layer tree.
# Activations stay replicated between blocks; only the projections' wide axis
# (and the attention heads living on it) shard over tp. The embed stays
# replicated — it is a gather on the decode hot path, and a vocab-sharded
# table would turn every step's first op into a collective; lm_head shards
# its CONTRACTION dim so the final logits come out replicated (one
# all-reduce) and the greedy argmax needs no cross-shard reduction.
SERVE_PARAM_SPECS: Dict[str, P] = {
    "embed": P(None, None),                 # [V, D] replicated (decode gather)
    "wq": P(None, None, "tp"),              # [L, D, H*Dh] heads over tp
    "wk": P(None, None, "tp"),              # [L, D, Hkv*Dh]
    "wv": P(None, None, "tp"),
    "wo": P(None, "tp", None),              # [L, H*Dh, D] row-parallel
    "w_gate": P(None, None, "tp"),          # [L, D, F] hidden over tp
    "w_up": P(None, None, "tp"),
    "w_down": P(None, "tp", None),          # [L, F, D] row-parallel
    "attn_norm": P(None, None),             # [L, D]
    "mlp_norm": P(None, None),
    "final_norm": P(None),                  # [D]
    "lm_head": P("tp", None),               # [D, V] contraction over tp
}

# KV page pools [L, pool, page, Kh, Dh]: the head axis rides the same tp
# split as the K/V projections that write it, so page writes and paged
# attention reads are shard-local (no resharding on the decode hot path).
SERVE_PAGE_SPEC = P(None, None, None, "tp", None)


def serve_param_specs(quant: str = "none") -> Dict[str, P]:
    """SERVE_PARAM_SPECS in the layout the engine actually holds: the fp tree,
    or the ``quantize_serve_params`` layout (``<k>_q`` int8 values take the fp
    weight's spec; ``<k>_s`` per-output-channel scales keep the OUTPUT axis
    sharding — for row-parallel weights the contraction axis that tp splits is
    reduced away in the scales, leaving them replicated)."""
    from dstack_tpu.workloads import quantize as quant_lib

    if not quant_lib.is_weight_only(quant):
        return dict(SERVE_PARAM_SPECS)
    specs: Dict[str, P] = {
        k: SERVE_PARAM_SPECS[k]
        for k in ("embed", "final_norm", "attn_norm", "mlp_norm")
    }
    for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"):
        spec = SERVE_PARAM_SPECS[k]
        specs[k + "_q"] = spec
        # scales [..., 1, N]: the contraction axis is a keepdims singleton, so
        # its mesh axis (if any) must not appear; keep only the output axis.
        parts = list(spec)
        parts[-2] = None
        specs[k + "_s"] = P(*parts)
    return specs


def serve_param_sharding(mesh: Mesh, quant: str = "none") -> Dict[str, NamedSharding]:
    return {
        k: NamedSharding(mesh, spec) for k, spec in serve_param_specs(quant).items()
    }


def validate_serve_mesh(cfg, mesh: Mesh) -> None:
    """Loud pre-compile validation of a serving mesh against the model config:
    tp must split whole heads (queries AND whole GQA KV groups), the MLP
    hidden, and the lm_head contraction — an uneven split would make GSPMD
    silently pad and reshard the decode hot path."""
    axes = dict(mesh.shape)
    unknown = set(axes) - {"dd", "tp"}
    if unknown:
        raise ValueError(
            f"serve mesh has unknown axes {sorted(unknown)}; expected (dd, tp)"
            f" — build it with sharding.make_serve_mesh"
        )
    tp = axes.get("tp", 1)
    if tp <= 1:
        return
    for name, dim in (
        ("n_heads", cfg.n_heads),
        ("n_kv_heads", cfg.n_kv_heads),
        ("d_ff", cfg.d_ff),
        ("d_model", cfg.d_model),
    ):
        if dim % tp:
            raise ValueError(
                f"serve mesh tp={tp} must divide {name}={dim} (whole"
                f" heads/channels per shard); adjust the mesh or the config"
            )


def shard_params(params: Dict[str, jax.Array], mesh: Mesh) -> Dict[str, jax.Array]:
    shardings = param_sharding(mesh)
    return {k: jax.device_put(v, shardings[k]) for k, v in params.items()}


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, BATCH_SPEC)


def pad_to_multiple(x: int, m: int) -> int:
    return int(math.ceil(x / m) * m)
