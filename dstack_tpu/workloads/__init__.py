"""Reference TPU workloads shipped with the orchestrator.

The reference repo ships torch/vLLM example workloads under examples/ (SURVEY §2.6:
parallelism lives in the user's container, the orchestrator only provides the cluster
contract). This package is the TPU analog — a MaxText-style Llama training workload in
pure JAX, sharded over a (dp, fsdp, tp, sp) mesh with ring attention for long context —
used by the shipped examples, the benchmark, and the multi-chip dry run.
"""

from dstack_tpu.workloads.config import LlamaConfig  # noqa: F401
