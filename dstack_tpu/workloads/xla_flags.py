"""Comm/compute-overlap compiler defaults for TPU training jobs.

The XLA flags every serious TPU training setup turns on (MaxText's proven
set, "Exploring the limits of Concurrency in ML Training on Google TPUs"):
the latency-hiding scheduler plus async collectives, so the all-gathers /
reduce-scatters / all-reduces that SPMD inserts for the (dp, fsdp, tp, sp)
shardings run concurrently with MXU compute instead of serializing the step.

This module is deliberately jax-free string composition so the SERVER can
import it: the TPU job configurator (server/services/jobs/configurators.py)
injects these defaults into every orchestrated TPU job's env, docker/tpu
bakes them into the default image, and the train entrypoint applies them
before JAX initializes its backend. User-provided values always win — the
merge is by flag name, never a blind overwrite.

Safety gate: the flags are libtpu-registered, and XLA dies on unknown
XLA_FLAGS entries on backends that don't register them (CPU jaxlib, the
axon dev plugin). `apply()` therefore only touches the environment when the
process is actually bound to a real TPU (PJRT_DEVICE=TPU — the contract the
runner/docker image sets) and DSTACK_TPU_OVERLAP_FLAGS is not "0".

Known tradeoff: the configurator/image inject the flags into the JOB env, so
a CPU-forced jax subprocess inside a TPU job (``JAX_PLATFORMS=cpu python``
without libtpu loaded) inherits flags its backend doesn't register and
aborts at init. Such a subprocess must clear them (``env -u XLA_FLAGS``) or
the job must opt out with DSTACK_TPU_OVERLAP_FLAGS=0 — the same contract
every flag-baked TPU training image (MaxText et al.) ships with; see
docs/guides/training-performance.md.
"""

from __future__ import annotations

import os
from typing import Dict, Mapping, Optional

# Flag -> value. Rationale per flag lives in docs/guides/training-performance.md
# (the user-facing table is generated from this dict — keep them in sync via
# tests/test_train_pipeline.py::TestXlaFlags).
OVERLAP_XLA_FLAGS: Dict[str, str] = {
    # The big one: schedule independent collectives/compute to overlap instead
    # of running the HLO sequence in order.
    "--xla_tpu_enable_latency_hiding_scheduler": "true",
    # Make the FSDP gather-on-use / reduce-scatter-on-grads asynchronous so
    # they hide under the matmuls that don't depend on them.
    "--xla_enable_async_all_gather": "true",
    "--xla_enable_async_collective_permute": "true",
    # Fuse adjacent async collectives and let a fused group span several
    # compute steps of the schedule.
    "--xla_tpu_enable_async_collective_fusion": "true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather": "true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps": "true",
    # Let the scheduler trade scoped HBM for overlap headroom.
    "--xla_tpu_overlap_compute_collective_tc": "true",
    "--xla_tpu_enable_all_experimental_scheduler_features": "true",
    # Split dp-sized ops so unequal-sized collectives still pipeline.
    "--xla_tpu_data_parallel_opt_different_sized_ops": "true",
    # Decompose einsum+collective patterns so each half can overlap.
    "--xla_tpu_decompose_all_gather_einsum": "true",
    "--xla_tpu_decompose_einsum_reduce_scatter": "true",
}

# Generation-specific additions layered OVER the shared base at env-compose
# time (the docker image bakes only the base — it doesn't know the chip; the
# configurator/entrypoint do, via TPU_ACCELERATOR_TYPE). The branch point:
# v5p-class training pods get more scoped vmem for collective double-
# buffering; v6e (Trillium) additionally offloads gather/reduce collectives
# to the SparseCores so the TensorCore schedule never stalls on them.
# Unknown/absent generation = base set only, exactly the pre-branch behavior.
GENERATION_XLA_FLAGS: Dict[str, Dict[str, str]] = {
    "v5p": {
        "--xla_tpu_scoped_vmem_limit_kib": "81920",
    },
    "v6e": {
        "--xla_tpu_scoped_vmem_limit_kib": "98304",
        "--xla_tpu_enable_sparse_core_collective_offload_all_gather": "true",
        "--xla_tpu_enable_sparse_core_collective_offload_all_reduce": "true",
    },
    "v6p": {
        "--xla_tpu_scoped_vmem_limit_kib": "98304",
        "--xla_tpu_enable_sparse_core_collective_offload_all_gather": "true",
        "--xla_tpu_enable_sparse_core_collective_offload_all_reduce": "true",
    },
}

# libtpu init args (parsed by libtpu itself, not XLA): host-offloaded DMA
# descriptors sized for multislice DCN transfers. Harmless on single slice.
OVERLAP_LIBTPU_ARGS: Dict[str, str] = {
    "--xla_tpu_enable_megascale_barrier": "true",
}

ENV_DISABLE = "DSTACK_TPU_OVERLAP_FLAGS"  # "0" opts a job out entirely


def chip_generation_from_env(env: Mapping[str, str]) -> str:
    """TPU_ACCELERATOR_TYPE ("v5p-16", "v5litepod-8", "v6e-8") -> generation
    ("v5p" / "v5e" / "v6e"), "" when unset or unrecognized. The jax-free twin
    of kernels.platform.chip_generation's env branch — this module is
    imported by the server and must never touch jax."""
    import re

    acc = str(env.get("TPU_ACCELERATOR_TYPE", ""))
    if acc.startswith("v5litepod"):
        return "v5e"
    m = re.match(r"(v\d+[a-z]*)", acc)
    return m.group(1) if m else ""


def generation_flags(gen: str = "") -> Dict[str, str]:
    """The full XLA default set for one chip generation: shared base +
    generation branch (unknown/"" = base only)."""
    merged = dict(OVERLAP_XLA_FLAGS)
    merged.update(GENERATION_XLA_FLAGS.get(gen, {}))
    return merged


def _parse(flags: str) -> Dict[str, Optional[str]]:
    """'--a=1 --b' -> {'--a': '1', '--b': None}, order preserved (dict)."""
    out: Dict[str, Optional[str]] = {}
    for tok in flags.split():
        name, sep, val = tok.partition("=")
        out[name] = val if sep else None
    return out


def _render(flags: Mapping[str, Optional[str]]) -> str:
    return " ".join(k if v is None else f"{k}={v}" for k, v in flags.items())


def compose(existing: str = "", defaults: Optional[Mapping[str, str]] = None) -> str:
    """Merge the overlap defaults UNDER an existing flag string: any flag the
    user already set (by name, whatever the value) is left untouched."""
    merged = dict(_parse(existing))
    for name, val in (defaults if defaults is not None else OVERLAP_XLA_FLAGS).items():
        merged.setdefault(name, val)
    return _render(merged)


def overlap_env(existing: Optional[Mapping[str, str]] = None) -> Dict[str, str]:
    """The env additions for one TPU job, composed against the job's own env
    (user flags win flag-by-flag). Returns {} when the job opted out."""
    existing = existing or {}
    if str(existing.get(ENV_DISABLE, "")) == "0":
        return {}
    defaults = generation_flags(chip_generation_from_env(existing))
    return {
        "XLA_FLAGS": compose(existing.get("XLA_FLAGS", ""), defaults),
        "LIBTPU_INIT_ARGS": compose(
            existing.get("LIBTPU_INIT_ARGS", ""), OVERLAP_LIBTPU_ARGS
        ),
    }


def apply(env: Optional[Mapping[str, str]] = None) -> Dict[str, str]:
    """Install the overlap defaults into os.environ — call BEFORE the first
    jax device/backend touch (XLA parses XLA_FLAGS at backend init).

    No-ops (returns {}) unless the process is bound to a real TPU
    (PJRT_DEVICE=TPU, the runner/docker contract): the flags are registered
    by libtpu and XLA hard-fails on unknown flags on other backends.
    """
    src = dict(env) if env is not None else dict(os.environ)
    if src.get("PJRT_DEVICE") != "TPU":
        return {}
    additions = overlap_env(src)
    for k, v in additions.items():
        os.environ[k] = v
    return additions
