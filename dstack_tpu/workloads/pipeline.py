"""Pipeline parallelism: GPipe microbatching over a ``pp`` mesh axis.

The model's stacked layers split into S contiguous stages sharded over
``pp``; microbatches flow stage-to-stage via ``ppermute`` on ICI inside one
``shard_map`` (the scaling-book pipelining recipe: a rotating buffer, S-1
bubble ticks, collectives explicit so XLA overlaps the permute with the next
tick's compute). The stage computation is model.transformer_block — the SAME
block the dense path runs, so pipelined and non-pipelined forward agree
numerically (tests assert this).

When to use: pp trades the all-gather bandwidth FSDP needs for point-to-point
activation transfers — the right axis once a model's layers no longer fit
even fully sharded, or across slower links. The mesh here is (dp, pp): data
parallelism composes outside the pp axis; within a stage the non-layer params
are replicated — composing fsdp/tp/sp INSIDE stages (per-stage sub-meshes) is
not implemented.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads.config import LlamaConfig

Params = Dict[str, jax.Array]

PP_MESH_AXES = ("dp", "pp")

LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
              "attn_norm", "mlp_norm")


def make_pp_mesh(dp: int = 1, pp: Optional[int] = None, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if pp is None:
        if n % dp != 0:
            raise ValueError(f"{n} devices not divisible by dp={dp}")
        pp = n // dp
    if dp * pp != n:
        raise ValueError(f"mesh {dp}x{pp} != {n} devices")
    return Mesh(np.array(devices).reshape(dp, pp), PP_MESH_AXES)


def stage_params_spec() -> Dict[str, P]:
    """Layer-stacked tensors shard their leading L axis over pp (L/S layers
    per stage, contiguous); everything else replicates."""
    specs = {k: P("pp") for k in LAYER_KEYS}
    specs.update({
        "embed": P(None, None),
        "final_norm": P(None),
        "lm_head": P(None, None),
    })
    return specs


def shard_params_pp(params: Params, mesh: Mesh) -> Params:
    specs = stage_params_spec()
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k])) for k, v in params.items()
    }


def pipelined_forward(
    params: Params,
    tokens: jax.Array,  # [B, T]; B must divide into n_micro microbatches
    cfg: LlamaConfig,
    mesh: Mesh,
    n_micro: int,
    return_hidden: bool = False,
) -> jax.Array:
    """Logits [B, T, V] fp32 — or the post-final-norm hidden [B, T, D] when
    `return_hidden` (feeds the chunked cross-entropy) — computed with the pp
    stages in a GPipe schedule.

    Schedule: n_micro + S - 1 ticks. At tick i, stage s processes microbatch
    (i - s) when 0 <= i - s < n_micro; activations hop one stage per tick via
    ppermute. Bubble ticks compute on garbage and are masked out — on TPU the
    uniform schedule (every shard does identical work every tick) is what lets
    XLA compile ONE tick body and overlap the permute with compute.
    """
    if cfg.n_layers % mesh.shape["pp"] != 0:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={mesh.shape['pp']}"
        )
    b, t = tokens.shape
    if b % n_micro != 0:
        raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")
    mb = b // n_micro
    adt = jnp.dtype(cfg.dtype)
    positions = jnp.arange(t)

    # Embed outside the pipeline (replicated over pp; sharded over dp).
    x = params["embed"].astype(adt)[tokens]  # [B,T,D]
    x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P("dp", None, None)))
    micro = x.reshape(n_micro, mb, t, -1)

    layer_stack = {k: params[k] for k in LAYER_KEYS}

    from jax.experimental.shard_map import shard_map

    def stage_run(stage_layers, xs):
        """Apply this stage's L/S layers to one microbatch activation."""

        def body(h, layer):
            return model_lib.transformer_block(h, layer, cfg, positions, None), None

        # Honor cfg.remat (and its policy) like the dense forward: without it
        # the backward pass stores every layer's residuals for every
        # microbatch and tick — defeating pp's purpose of fitting models.
        body_fn = (
            jax.checkpoint(body, prevent_cse=True,
                           policy=model_lib.remat_policy_of(cfg))
            if cfg.remat else body
        )
        out, _ = jax.lax.scan(body_fn, xs, stage_layers)
        return out

    # Static stage count (jax.lax has no axis_size; the mesh is in scope).
    pp = mesh.shape["pp"]

    def pipeline_body(stage_layers, micro_local):
        # Inside shard_map: stage_layers has the LOCAL [L/S, ...] slice;
        # micro_local is the dp-local microbatch stream, replicated over pp.
        sid = jax.lax.axis_index("pp")
        n_mb = micro_local.shape[0]
        ticks = n_mb + pp - 1

        perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, i):
            recv, outputs = carry
            feed_idx = jnp.clip(i, 0, n_mb - 1)
            inp = jnp.where(sid == 0, micro_local[feed_idx], recv)
            out = stage_run(stage_layers, inp)
            # Hop to the next stage; the wrap-around into stage 0 is ignored
            # (stage 0 always feeds from `micro_local`).
            recv_next = jax.lax.ppermute(out, "pp", perm_fwd)
            out_idx = i - (pp - 1)
            valid = (sid == pp - 1) & (out_idx >= 0)
            outputs = jax.lax.cond(
                valid,
                lambda o: o.at[jnp.clip(out_idx, 0, n_mb - 1)].set(out),
                lambda o: o,
                outputs,
            )
            return (recv_next, outputs), None

        init = (
            jnp.zeros_like(micro_local[0]),
            jnp.zeros_like(micro_local),
        )
        (_, outputs), _ = jax.lax.scan(tick, init, jnp.arange(ticks))
        # Only the last stage holds real outputs; replicate across pp so the
        # caller sees one coherent [n_micro, mb, T, D].
        return jax.lax.psum(
            jnp.where(sid == pp - 1, outputs, jnp.zeros_like(outputs)), "pp"
        )

    outputs = shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=({k: P("pp") for k in LAYER_KEYS}, P(None, "dp", None, None)),
        out_specs=P(None, "dp", None, None),
        check_rep=False,
    )(layer_stack, micro)

    h = outputs.reshape(b, t, -1)
    h = model_lib._rms_norm(h, params["final_norm"], cfg.norm_eps)
    if return_hidden:
        return h
    logits = jnp.einsum("btd,dv->btv", h, params["lm_head"].astype(adt),
                        preferred_element_type=jnp.float32)
    return logits


def pipelined_loss_fn(
    params: Params,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: LlamaConfig,
    mesh: Mesh,
    n_micro: int,
) -> jax.Array:
    chunk = model_lib.pick_loss_chunk(cfg, tokens.shape[1])
    if chunk:
        hidden = pipelined_forward(params, tokens, cfg, mesh, n_micro,
                                   return_hidden=True)
        lm_head = params["lm_head"].astype(jnp.dtype(cfg.dtype))
        total_nll, total_cnt = model_lib._chunked_nll(hidden, lm_head, targets, chunk)
        return total_nll / jnp.maximum(total_cnt, 1)
    return model_lib.masked_ce(
        pipelined_forward(params, tokens, cfg, mesh, n_micro), targets
    )
