"""Workload telemetry emitter: the workload end of the workload->runner->server
metrics channel.

Design contract (the whole point of this module):

* **Zero dependencies.** stdlib only — importable before (or without) jax, so
  a crashed backend init can still emit lifecycle marks. jax is imported
  lazily and only when the profiler control hook actually fires.
* **Never blocks, never throws into the caller.** ``emit()`` appends to a
  bounded in-memory buffer under a lock held for microseconds; a full buffer
  DROPS the point and increments ``dropped`` (a counter the flusher reports
  downstream) instead of back-pressuring the train step. Sidecar write
  errors are swallowed and counted — a full disk degrades observability,
  never the workload.
* **Sidecar file protocol.** A background thread flushes buffered points as
  JSON lines appended to the path in ``DSTACK_TPU_TELEMETRY_PATH`` (set by
  the runner agent, which tails the file and ships new lines inside its
  ``/api/metrics`` sample — runner/src/executor.cpp). No emitter->agent RPC:
  the file IS the queue, and it survives the workload process.
* **Control hook.** The agent requests on-demand profiling by atomically
  writing ``<path>.ctl`` (``{"id": N, "cmd": "profile", "seconds": S}``).
  The flusher polls the file each tick; a new id starts
  ``jax.profiler.start_trace`` into ``<dir(path)>/profile/<id>`` and stops it
  ``S`` seconds later, emitting ``profile_start``/``profile_end`` marks (the
  end mark carries the artifact path the operator retrieves).

Point schema (one JSON object per line, all optional but ``ts``/``kind``):

* ``kind="step"``  — per-train-step: ``step``, ``step_time_s``,
  ``tokens_per_sec``, ``mfu``, ``tf_per_sec``, ``loss``, ``input_wait_s``,
  ``collective_wait_s`` (the timed psum fence train.py brackets the step
  with — what the server's gang-health skew attribution reads).
* every point also carries the emitting host's identity — ``host``
  (hostname), ``proc`` (TPU worker id / node rank), ``slice`` (MegaScale
  slice id) — so a gang's N sidecar streams stay attributable per host
  after the server joins them (services/gang_health.py).
* ``kind="engine"`` — serving engine gauges: ``queue_depth``, ``active``,
  ``generated_tokens``, ``prefix_hit_rate``, ``spec_accept_rate``, ...
* ``kind="mark"``  — lifecycle: ``event`` in {``run_start``, ``compile_start``,
  ``compile_end``, ``checkpoint_start``, ``checkpoint_end`` (carries the
  measured ``blocked_s`` — the only time the train thread stalled),
  ``checkpoint_saved``, ``checkpoint_error``, ``restart``, ``run_end``,
  ``profile_start``, ``profile_end``, ``profile_error``} plus free fields.
* ``kind="emitter"`` — the emitter's own health: ``dropped``,
  ``write_errors`` (emitted only when the counters advance).

The server's goodput ledger (server/services/metrics.py compute_goodput)
derives productive/compile/input/restart attribution from exactly these
kinds, so emit marks honestly: ``compile_start`` before the first traced
step, ``compile_end`` when it returns.
"""

from __future__ import annotations

import datetime
import json
import os
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

ENV_PATH = "DSTACK_TPU_TELEMETRY_PATH"

# Buffer/flush defaults: at one point per second-scale train step a 4096-point
# buffer holds over an hour of backlog; the 0.25 s flush keeps the agent's
# tail near-real-time without measurable file-IO pressure.
DEFAULT_CAPACITY = 4096
DEFAULT_FLUSH_INTERVAL = 0.25


def _iso_now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


def _host_identity() -> Dict[str, Any]:
    """Per-host identity stamped on every point so a gang's N streams stay
    attributable after they merge server-side (services/gang_health.py):
    ``host`` (hostname), ``proc`` (TPU worker / node rank when the agent's
    cluster env is present), ``slice`` (MegaScale slice id on multislice).
    Env-only + stdlib — jax may not be importable yet when the first marks
    are emitted."""
    ident: Dict[str, Any] = {}
    try:
        ident["host"] = socket.gethostname()
    except Exception:
        pass
    for field, names in (
        ("proc", ("TPU_WORKER_ID", "DSTACK_NODE_RANK")),
        ("slice", ("MEGASCALE_SLICE_ID",)),
    ):
        for name in names:
            raw = os.environ.get(name)
            if raw:
                try:
                    ident[field] = int(raw)
                except ValueError:
                    continue  # unparsable -> try the next fallback var
                break
    return ident


class _JaxProfiler:
    """Default control-hook profiler: jax.profiler trace capture. Imported
    lazily so the emitter stays importable (and the flusher harmless) in
    processes that never load jax."""

    def start(self, logdir: str) -> None:
        import jax.profiler

        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)

    def stop(self) -> None:
        import jax.profiler

        jax.profiler.stop_trace()


class TelemetryEmitter:
    """Bounded, never-blocking telemetry channel to the runner agent.

    ``profiler`` is injectable for tests (needs ``start(logdir)``/``stop()``);
    ``None`` selects the lazy jax profiler."""

    def __init__(
        self,
        path: str,
        capacity: int = DEFAULT_CAPACITY,
        flush_interval: float = DEFAULT_FLUSH_INTERVAL,
        profiler: Optional[Any] = None,
    ) -> None:
        self.path = path
        self.capacity = max(1, int(capacity))
        self.flush_interval = flush_interval
        self.enabled = True
        self.dropped = 0
        self.write_errors = 0
        self.profile_errors = 0
        self._buf: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._closed = threading.Event()
        self._profiler = profiler if profiler is not None else _JaxProfiler()
        self.identity: Dict[str, Any] = _host_identity()
        self._profile_id = 0  # last handled control-command id
        self._profile_stop_at: Optional[float] = None
        self._profile_artifact: Optional[str] = None
        self._ctl_sig: Optional[tuple] = None  # (mtime_ns, size) of last read ctl
        self._reported = (0, 0)  # (dropped, write_errors) already shipped
        self._thread = threading.Thread(
            target=self._flush_loop, name="telemetry-flush", daemon=True
        )
        self._thread.start()

    # -- the hot path ------------------------------------------------------

    def emit(self, kind: str, **fields: Any) -> None:
        """Queue one point. Safe to call from any thread, including the train
        step's — a full buffer drops (and counts), nothing here raises."""
        try:
            point = {"ts": _iso_now(), "kind": kind}
            # Identity first so an explicit field (tests, multi-tenant
            # harnesses) can override what the env derived.
            point.update(self.identity)
            point.update(fields)
            with self._lock:
                if len(self._buf) >= self.capacity:
                    self.dropped += 1
                    return
                self._buf.append(point)
        except Exception:
            # The emitter must never take the workload down, full stop.
            self.dropped += 1

    def step(self, step: int, step_time_s: float, **fields: Any) -> None:
        self.emit("step", step=step, step_time_s=step_time_s, **fields)

    def mark(self, event: str, **fields: Any) -> None:
        self.emit("mark", event=event, **fields)

    def set_identity(self, **fields: Any) -> None:
        """Merge identity fields stamped on every subsequent point (the train
        entrypoint refines ``proc`` with jax.process_index() once the backend
        is up — the env derivation above may be absent in local runs)."""
        try:
            self.identity.update(fields)
        except Exception:
            pass

    # -- flushing ----------------------------------------------------------

    def flush(self, timeout: float = 2.0) -> None:
        """Best-effort synchronous drain (used at run end so the final points
        are durable before the process exits). Never raises."""
        try:
            self._flush_once()
        except Exception:
            self.write_errors += 1
        # The background thread may be mid-write; give it a beat.
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._buf:
                    return
            time.sleep(0.01)

    def close(self, timeout: float = 2.0) -> None:
        """Flush and stop the background thread. Idempotent, never raises."""
        if self._closed.is_set():
            return
        self._closed.set()
        self._wake.set()
        try:
            self._thread.join(timeout)
        except Exception:
            pass
        try:
            self._stop_profile_if_due(force=True)
        except Exception:
            pass
        self.flush(timeout=0)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "buffered": len(self._buf),
                "dropped": self.dropped,
                "write_errors": self.write_errors,
                "profile_errors": self.profile_errors,
            }

    def _flush_loop(self) -> None:
        while not self._closed.wait(self.flush_interval):
            try:
                self._poll_control()
            except Exception:
                self.profile_errors += 1
            try:
                self._stop_profile_if_due()
            except Exception:
                self.profile_errors += 1
            try:
                self._flush_once()
            except Exception:
                self.write_errors += 1
        # Final drain on close.
        try:
            self._flush_once()
        except Exception:
            self.write_errors += 1

    def _flush_once(self) -> None:
        with self._lock:
            if not self._buf:
                batch: List[dict] = []
            else:
                batch = list(self._buf)
                self._buf.clear()
            # Report counter advances as their own point so drops are visible
            # downstream even though the dropped points themselves are gone.
            counters = (self.dropped, self.write_errors)
            if counters != self._reported:
                batch.append(
                    {
                        "ts": _iso_now(),
                        "kind": "emitter",
                        "dropped": counters[0],
                        "write_errors": counters[1],
                    }
                )
                self._reported = counters
        if not batch:
            return
        lines = []
        for point in batch:
            try:
                lines.append(json.dumps(point, default=str))
            except Exception:
                self.dropped += 1
        if not lines:
            return
        try:
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
        except Exception:
            # Count the batch as dropped: it is gone, and the write error
            # alone would undercount the loss.
            self.write_errors += 1
            self.dropped += len(lines)

    # -- the profiler control hook ----------------------------------------

    @property
    def _ctl_path(self) -> str:
        return self.path + ".ctl"

    def _poll_control(self) -> None:
        try:
            st = os.stat(self._ctl_path)
        except OSError:
            return
        sig = (st.st_mtime_ns, st.st_size)
        if sig == self._ctl_sig:
            return
        with open(self._ctl_path, "r", encoding="utf-8") as f:
            cmd = json.loads(f.read())
        if not isinstance(cmd, dict) or cmd.get("cmd") != "profile":
            self._ctl_sig = sig
            return
        cmd_id = int(cmd.get("id") or 0)
        if cmd_id <= self._profile_id:
            self._ctl_sig = sig
            return  # already handled (mtime jitter, agent rewrite)
        if self._profile_stop_at is not None:
            # One capture at a time — but do NOT consume the command: leaving
            # the signature unrecorded makes the next tick retry it, so a
            # request that arrived mid-capture starts when this one stops
            # instead of silently vanishing.
            return
        self._ctl_sig = sig
        self._profile_id = cmd_id
        seconds = min(max(float(cmd.get("seconds") or 5.0), 0.1), 600.0)
        artifact = os.path.join(os.path.dirname(self.path), "profile", str(cmd_id))
        # Mark (and flush) BEFORE starting: on a loaded host start_trace can
        # block for tens of seconds against the training thread, and the
        # operator polling the metrics channel should see the request was
        # picked up rather than silence.
        self.mark("profile_start", profile_id=cmd_id, seconds=seconds, artifact=artifact)
        try:
            self._flush_once()
        except Exception:
            self.write_errors += 1
        try:
            self._profiler.start(artifact)
        except Exception as e:
            self.profile_errors += 1
            self.mark("profile_error", profile_id=cmd_id, error=str(e)[:200])
            return
        self._profile_artifact = artifact
        # The capture window counts from when tracing actually began (start
        # may block under load); `seconds` is a minimum, stop lands on the
        # next flush tick after it elapses.
        self._profile_stop_at = time.monotonic() + seconds

    def _stop_profile_if_due(self, force: bool = False) -> None:
        if self._profile_stop_at is None:
            return
        if not force and time.monotonic() < self._profile_stop_at:
            return
        artifact, self._profile_artifact = self._profile_artifact, None
        self._profile_stop_at = None
        try:
            self._profiler.stop()
        except Exception as e:
            self.profile_errors += 1
            self.mark("profile_error", profile_id=self._profile_id, error=str(e)[:200])
            return
        self.mark("profile_end", profile_id=self._profile_id, artifact=artifact)


class NullEmitter:
    """The disabled emitter (no DSTACK_TPU_TELEMETRY_PATH): same surface, no
    buffer, no thread — workloads call it unconditionally and pay nothing."""

    enabled = False
    path = None
    dropped = 0
    write_errors = 0

    def __init__(self) -> None:
        self.identity: Dict[str, Any] = {}

    def emit(self, kind: str, **fields: Any) -> None:
        pass

    def set_identity(self, **fields: Any) -> None:
        pass

    def step(self, step: int, step_time_s: float, **fields: Any) -> None:
        pass

    def mark(self, event: str, **fields: Any) -> None:
        pass

    def flush(self, timeout: float = 0.0) -> None:
        pass

    def close(self, timeout: float = 0.0) -> None:
        pass

    def stats(self) -> Dict[str, int]:
        return {"buffered": 0, "dropped": 0, "write_errors": 0, "profile_errors": 0}


_emitter: Optional[Any] = None
_emitter_lock = threading.Lock()


def get_emitter() -> Any:
    """Process-wide emitter, created on first use from DSTACK_TPU_TELEMETRY_PATH
    (the runner agent sets it; unset = NullEmitter, telemetry off)."""
    global _emitter
    with _emitter_lock:
        if _emitter is None:
            path = os.environ.get(ENV_PATH, "")
            _emitter = TelemetryEmitter(path) if path else NullEmitter()
        return _emitter


def configure(emitter: Optional[Any]) -> Any:
    """Swap the process-wide emitter (tests; None resets to re-read the env).
    Returns the previous emitter WITHOUT closing it — the caller owns both."""
    global _emitter
    with _emitter_lock:
        prev, _emitter = _emitter, emitter
        return prev
