"""Continuous-batching inference engine over the llama workload.

The serving half of the north star ("serve millions of users"): an in-flight
batching token loop in the vLLM/JetStream mold, built on the repo's own model
stack —

- **Paged KV cache**: one page pool per layer (``[N_pages, page, Kh, D]``);
  each request owns a page table of pool indices, so sequences of wildly
  different lengths share HBM without reserving max_seq_len each. Pages are
  allocated on demand as decode crosses page boundaries and returned to the
  free list the step a request finishes.
- **Prefill/decode split**: new requests' prompts run as a separate batched
  prefill (blockwise/flash-style attention from ``attention.py``, KV scattered
  into their pages), while the running decode batch advances one token per
  step through a single-query paged-attention path
  (``attention.paged_decode_attention``).
- **Per-step admission**: every engine step first admits queued requests into
  free decode slots (pages permitting), so short requests drain out and new
  ones slide in without ever stalling the batch — the continuous-batching win
  over static batching that ``bench.py bench_serve`` measures.
- **Streaming**: tokens are emitted per step; the aiohttp app turns them into
  SSE events that ride the proxy's unbuffered pass-through (PR 2) to clients.

Tier 2 (all three off by default, each proven token-identical to the tier-1
engine by tests/test_serve_tier2.py):

- **Chunked prefill** (``prefill_chunk > 0``): prompts run at most
  ``prefill_chunk`` tokens per engine step, interleaved with decode — one
  giant prompt raises its own TTFT instead of everyone's inter-token latency.
  Chunks attend over the paged prefix via ``attention.paged_chunk_attention``
  (or the Pallas twin), the multi-query generalization of the decode path.
- **Prefix caching** (``prefix_cache=True``): full KV pages of prompt blocks
  are registered in a refcounted hash-chain (``PrefixCache``); a new request
  sharing the same prompt prefix reuses those pages and prefills only its
  suffix. Cached pages are sealed — never written again — so copy-on-write
  degenerates to allocate-on-divergence, and LRU leaf eviction returns idle
  blocks to the allocator before preemption ever triggers.
- **Speculative decode** (``spec_tokens=k``): a host-side n-gram proposer
  drafts k tokens per slot and one batched verify forward scores all of them;
  the greedy accept/reject rule emits between 1 and k+1 tokens per step and
  is token-identical to non-speculative greedy decode by construction.

Everything runs under ``JAX_PLATFORMS=cpu`` (tests/bench: 1 device, tiny
config); on TPU the same jitted prefill/decode functions land on the chip.
Decoding is greedy (argmax) — deterministic, which is what makes the
continuous-vs-sequential token-equivalence test meaningful.

A numerics caveat on "token-identical": the guarantee is exact at the
scheduling level (what gets proposed/accepted/emitted given the logits) and
bit-exact end to end when activations are fp32 — which is how the tier-2
tests and ``bench_serve`` run. With bf16 activations, chunked prefill and
the C > 1 verify forward reduce the same attention sums in a different
order than the whole-prompt / C == 1 paths; the fp32 accumulators still
round to bf16 between layers, so a one-ulp difference can flip a greedy
argmax at a near-tie and the streams can diverge from that token on. That
is inherent to reordering floating-point reductions (flash attention has
the same property), not a scheduling bug — validate strict identity in
fp32, and treat bf16 divergence-at-near-ties as expected noise.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import json
import logging
import os
import re
import threading
import time
from typing import Callable, Deque, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dstack_tpu.core import tracing
from dstack_tpu.workloads import model as model_lib
from dstack_tpu.workloads import quantize as quant_lib
from dstack_tpu.workloads import sharding as sharding_lib
from dstack_tpu.workloads.attention import (
    blockwise_attention,
    paged_chunk_attention,
    paged_decode_attention,
)
from dstack_tpu.workloads.config import LlamaConfig, get_config
from dstack_tpu.workloads.kernels.paged import (
    paged_chunk_attention_pallas,
    paged_decode_attention_pallas,
)

logger = logging.getLogger(__name__)

_WEIGHT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
_NORM_KEYS = ("attn_norm", "mlp_norm")
_LAYER_KEYS = _WEIGHT_KEYS + _NORM_KEYS

DECODE_IMPLS = ("auto", "xla", "pallas")


def resolve_decode_impl(impl: str) -> str:
    """"auto" = the Pallas paged kernel on TPU (pages stay in HBM, one DMA per
    page), the XLA gather elsewhere (interpret-mode Pallas is orders slower
    than compiled XLA on CPU — tests/bench opt in explicitly)."""
    if impl != "auto":
        return impl
    from dstack_tpu.workloads.kernels.platform import is_tpu_default_device

    return "pallas" if is_tpu_default_device() else "xla"


def quantize_serve_params(
    params: dict, consume: bool = False, mode: str = "int8"
) -> dict:
    """Weight-only quantization for serving: every projection weight becomes
    a quantized tensor + per-output-channel fp32 scales (``<k>_q`` /
    ``<k>_s``), halving weight HBM vs bf16; embeddings and norms stay
    full-precision (the embed is a gather, the norms are tiny). ``mode`` is
    "int8" or "fp8" — both dequantize on use, so fp8 storage works on any
    chip generation (it is HBM compression, not an fp8 matmul; the v5p+ gate
    applies only to training's quant=fp8 MXU path).

    With ``consume=True`` the input dict is drained as it is quantized: each
    fp projection weight is popped (dropping its last reference, so the
    device buffer frees) the moment its int8 twin exists — peak memory is the
    fp tree plus ONE int8 copy, never both full trees. This is the restore
    path's contract: a real checkpoint's weights quantize in place of the
    just-restored fp leaves."""
    out = {
        "embed": params.pop("embed") if consume else params["embed"],
        "final_norm": params.pop("final_norm") if consume else params["final_norm"],
        "attn_norm": params.pop("attn_norm") if consume else params["attn_norm"],
        "mlp_norm": params.pop("mlp_norm") if consume else params["mlp_norm"],
    }
    for k in _WEIGHT_KEYS + ("lm_head",):
        w = params.pop(k) if consume else params[k]
        qw = quant_lib.quantize_weight(w, mode=mode)  # contraction = 2nd-to-last
        del w
        out[k + "_q"] = qw.values
        out[k + "_s"] = qw.scales
    return out


def _serve_layer_keys(quant: str):
    if not quant_lib.is_weight_only(quant):
        return _LAYER_KEYS
    return tuple(
        f"{k}_{suffix}" for k in _WEIGHT_KEYS for suffix in ("q", "s")
    ) + _NORM_KEYS


def parse_mesh_arg(spec: str) -> Optional[Mesh]:
    """CLI serve-mesh spec -> Mesh: "tp4" (dd absorbs the rest of the slice),
    "dd2xtp4" (explicit replica axis), "" / "none" -> meshless."""
    if not spec or spec == "none":
        return None
    m = re.fullmatch(r"(?:dd(\d+)x)?tp(\d+)", spec)
    if m is None:
        raise ValueError(
            f"bad mesh spec {spec!r}; expected tpN or ddMxtpN (e.g. tp4,"
            f" dd2xtp4)"
        )
    dd = int(m.group(1)) if m.group(1) else None
    return sharding_lib.make_serve_mesh(tp=int(m.group(2)), dd=dd)


def load_serve_params(
    checkpoint_dir: str,
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
    quant: str = "none",
    step: Optional[int] = None,
) -> Tuple[dict, dict]:
    """Restore real weights for the engine from a train checkpoint — the
    elastic re-shard path of ``CheckpointManager`` pointed at serving:

    - the template is ``jax.eval_shape`` over ``init_params`` (no synthetic
      tree is ever initialized), each leaf a ShapeDtypeStruct carrying its
      SERVE sharding — so a checkpoint saved on a dp/fsdp train mesh lands
      directly in the tp(/dd) layout, one host->device transfer per leaf;
    - only the ``.params`` subtree's shard bytes are read (a full TrainState
      checkpoint's optimizer moments — 2x the param bytes — never leave
      disk), via ``restore_subtree``'s prefix matching, which also accepts
      params-only checkpoints;
    - with a weight-only ``quant`` ("int8" / "fp8"),
      ``quantize_serve_params(consume=True)`` drains the fp tree as it
      quantizes: peak memory is the fp params plus one quantized leaf,
      never two full trees.

    Returns ``(params, manifest)`` — params in the layout ``ServeEngine``
    expects for the given ``quant``."""
    from dstack_tpu.workloads import checkpoint as checkpoint_lib

    quant_lib.check_quant(quant)
    if mesh is not None:
        sharding_lib.validate_serve_mesh(cfg, mesh)
    manager = checkpoint_lib.CheckpointManager(checkpoint_dir)
    shapes = jax.eval_shape(
        lambda k: model_lib.init_params(cfg, k), jax.random.PRNGKey(0)
    )
    shardings = (
        sharding_lib.serve_param_sharding(mesh, "none") if mesh is not None else {}
    )
    template = {
        k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=shardings.get(k))
        for k, v in shapes.items()
    }
    params, manifest = manager.restore_subtree(
        template, step=step, prefix=".params"
    )
    if quant_lib.is_weight_only(quant):
        params = quantize_serve_params(params, consume=True, mode=quant)
    return params, manifest


def load_draft_params(
    checkpoint_dir: str,
    cfg: LlamaConfig,
    mesh: Optional[Mesh] = None,
    step: Optional[int] = None,
) -> Tuple[dict, dict]:
    """Restore the speculative-decode draft head — the ``.draft`` subtree a
    ``train.py --draft-head`` run saved (DraftTrainState) — for --spec-model.

    The head's depth/width are whatever was trained, so the template comes
    from the MANIFEST's ``.draft`` leaves, not from a config-derived
    ``eval_shape``: each leaf restores at its saved shape. The head stays
    REPLICATED on a serve mesh (it is a few 100k params; sharding it would
    trade an all-gather per proposal step for nothing) and never quantizes —
    weight-only quant pays off on the target's GB-scale projections, not
    here. Returns ``(draft_params, manifest)``."""
    from dstack_tpu.workloads import checkpoint as checkpoint_lib

    manager = checkpoint_lib.CheckpointManager(checkpoint_dir)
    if step is None:
        step = manager.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no complete checkpoint in {checkpoint_dir}"
            )
    manifest = manager.read_manifest(step)
    rep = NamedSharding(mesh, P()) if mesh is not None else None
    template = {}
    for leaf in manifest["leaves"]:
        key = leaf["key"]
        if not key.startswith(".draft["):
            continue
        name = key[len(".draft"):].strip("[]'\"")
        template[name] = jax.ShapeDtypeStruct(
            tuple(leaf["shape"]), np.dtype(leaf["dtype"]), sharding=rep
        )
    if not template:
        raise ValueError(
            f"checkpoint step {step} in {checkpoint_dir} has no .draft"
            f" subtree — distill one with `train.py --draft-head`"
        )
    d = cfg.d_model
    if tuple(template["w_fuse"].shape) != (2 * d, d):
        raise ValueError(
            f"draft head was trained for d_model"
            f" {template['w_fuse'].shape[1]}, engine config has {d}"
        )
    draft, manifest = manager.restore_subtree(
        template, step=step, prefix=".draft"
    )
    return draft, manifest


def _proj(x: jax.Array, layer: dict, key: str, adt, quant: str) -> jax.Array:
    """x[..., K] @ layer[key] in adt: fp einsum, or weight-only int8/fp8
    (the dequant is dtype-agnostic: values.astype(x.dtype) * scales)."""
    if quant_lib.is_weight_only(quant):
        return quant_lib.weight_only_matmul(
            x, layer[key + "_q"], layer[key + "_s"]
        ).astype(adt)
    w = layer[key].astype(adt)
    return jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(adt)


def _logits(x: jax.Array, params: dict, adt, quant: str) -> jax.Array:
    if quant_lib.is_weight_only(quant):
        return quant_lib.weight_only_matmul(
            x, params["lm_head_q"], params["lm_head_s"]
        )
    return jax.lax.dot_general(
        x, params["lm_head"].astype(adt), (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Engine knobs, orthogonal to the model config (LlamaConfig)."""

    page_size: int = 16        # tokens per KV page
    num_pages: int = 256       # pool size, shared by all slots (per layer)
    max_batch: int = 8         # decode slots = max in-flight sequences
    max_seq: int = 0           # page-table width in tokens (0 = cfg.max_seq_len)
    # "continuous" admits into any free slot every step; "static" only admits
    # when the whole batch has drained (the classic static-batching baseline
    # bench_serve compares against).
    policy: str = "continuous"
    eos_id: Optional[int] = None
    max_new_default: int = 16
    # Decode attention: "auto" = Pallas paged kernel on TPU / XLA gather on
    # CPU; "xla"/"pallas" force one (kernels/paged.py).
    decode_impl: str = "auto"
    # "int8" / "fp8" = weight-only quantization (quantize_serve_params):
    # projection weights stored int8 or fp8-e4m3 + per-channel scales,
    # dequantized on use (works on any chip — storage only, no fp8 matmul).
    quant: str = "none"
    # Max prompt tokens prefilled per request per engine step (0 = whole
    # prompt in one batched prefill, the tier-1 behavior). With chunking, a
    # long prompt interleaves with decode steps instead of stalling them.
    prefill_chunk: int = 0
    # Cross-request prefix caching: full KV pages of prompt blocks are kept in
    # a refcounted registry after prefill; later requests sharing the prefix
    # skip recomputing it. Evicted LRU when the allocator runs dry.
    prefix_cache: bool = False
    # Speculative decode: k draft tokens per slot from an n-gram proposer,
    # verified in one batched forward (0 = one token per step, tier-1).
    spec_tokens: int = 0
    # Model-based drafting (engine built with draft_params, serve CLI
    # --spec-model): per-request windowed accept tracking falls a slot back
    # to the n-gram proposer when the head underperforms — a mismatched or
    # stale head degrades to today's behavior, never below it. The window is
    # spec STEPS (not tokens); fallback triggers only once it is full, so a
    # cold start never flaps. threshold <= 0 disables fallback.
    spec_fallback_window: int = 16
    spec_fallback_threshold: float = 0.1
    # Engine-level sliding window (spec steps) behind the windowed accept
    # rate on /stats and X-Dstack-Spec-Accept-Rate — the lifetime average
    # masks a proposer that has gone cold on the current traffic.
    spec_window: int = 64


class TokenEvent(NamedTuple):
    req_id: str
    token: int
    index: int   # 0-based position in the generated sequence
    done: bool


@dataclasses.dataclass
class GenRequest:
    req_id: str
    prompt: List[int]          # tokens prefilled on (re)admission
    max_new_tokens: int
    eos_id: Optional[int]
    submitted_t: float = 0.0
    tokens: List[int] = dataclasses.field(default_factory=list)  # generated
    done: bool = False
    preemptions: int = 0
    # Generated tokens already folded into `prompt` by earlier preemptions —
    # the resume prompt must append only tokens[absorbed:], or a second
    # preemption would duplicate the first one's tokens into the context.
    absorbed: int = 0
    # Tier-2 prefill progress for the CURRENT admission: prompt tokens whose
    # KV is already in pages (cache hits + chunks done). Reset on admission;
    # < len(prompt) means the slot is mid-prefill and not yet decoding.
    pos: int = 0
    # Prompt tokens served from the prefix cache at last admission (stats).
    cached_tokens: int = 0
    # -- request-level observability (ISSUE 18) ---------------------------
    # Host-side lifecycle stamps (time.monotonic), set once each: admission
    # into a slot, first prefill chunk launched, first generated token (TTFT),
    # and completion. Preemption re-admissions do NOT restamp — queue wait and
    # prefill attribute to the request's first pass; re-prefill cost shows up
    # in `preemptions` and the decode span instead. All of this is host-only
    # bookkeeping: the device sees the exact same program either way.
    trace_id: Optional[str] = None   # proxy-issued X-Dstack-Trace-Id
    admitted_t: float = 0.0
    prefill_start_t: float = 0.0
    first_token_t: float = 0.0
    finished_t: float = 0.0
    # Per-token emission stamps (ITL samples); bounded by max_new_tokens.
    token_times: List[float] = dataclasses.field(default_factory=list)
    # Per-request speculative-decode accounting (engine totals aggregate
    # these; the flight recorder reports them per trace).
    spec_proposed: int = 0
    spec_accepted: int = 0
    # Speculative-decode proposer state, built lazily on the first draft:
    # the full emitted stream (prompt + generated — invariant under
    # preemption refolds, which only move tokens between the two lists) and
    # its trailing-n-gram continuation index. _emit keeps both current.
    spec_ctx: Optional[List[int]] = None
    spec_index: Optional[dict] = None
    # Model-based drafting: whether this request still uses the draft head
    # (False after a windowed-accept-rate fallback — per-slot, permanent for
    # the request's life), and the (proposed, accepted) samples of its most
    # recent spec steps (a deque maxlen = ecfg.spec_fallback_window, created
    # by the engine on the first spec step).
    draft_ok: bool = True
    spec_recent: Optional[Deque[Tuple[int, int]]] = None


def _rope_single(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding for one token per row: x [S,H,D], positions [S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, D/2]
    cos = jnp.cos(angles)[:, None, :]
    sin = jnp.sin(angles)[:, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _serve_shardings(quant: str, mesh: Mesh):
    """(param shardings, page-pool sharding, replicated) for a serve mesh —
    what the jitted engine fns pin their in/out shardings to. Everything the
    host builds per step (tokens, page tables, write maps) stays replicated;
    only the weights and the KV pools shard."""
    param_sh = sharding_lib.serve_param_sharding(mesh, quant)
    page_sh = NamedSharding(mesh, sharding_lib.SERVE_PAGE_SPEC)
    rep = NamedSharding(mesh, P())
    return param_sh, page_sh, rep


@functools.lru_cache(maxsize=None)
def make_prefill_fn(cfg: LlamaConfig, quant: str = "none",
                    mesh: Optional[Mesh] = None, with_hidden: bool = False):
    """jit'd (params, tokens, k_pages, v_pages, write_page, write_off, lens)
    -> (next_tokens, k_pages, v_pages). Memoized on the (frozen) config +
    quant mode (+ mesh) so every engine over the same model shares one jit
    cache — bench variants don't re-compile per engine.

    ``with_hidden`` (the draft-head engines) inserts the last valid
    position's final hidden state [B, D] after next_tokens in the returns —
    the conditioning input for the FIRST model-based proposal after this
    prefill; without it the head would sit blind until the first verify.

    With a serve ``mesh``, the same trace runs tp-sharded: projections and
    attention heads split per SERVE_PARAM_SPECS, pages per SERVE_PAGE_SPEC
    (head axis), host-side inputs replicated — GSPMD inserts the Megatron
    pair of all-reduces (after wo and w_down) and the lm_head reduction; the
    host-side scheduling code above never changes.

    tokens [B, T] right-padded prompts; write_page/write_off [B, T] map each
    token position into the page pool (pool-size index = dropped write, which
    is how padding — and padded batch rows — never touch the cache); lens [B]
    true prompt lengths. Runs the same blockwise causal attention as training
    forward(); returns the greedy next token after each prompt's LAST valid
    position. Cache buffers are donated: the update is in-place on device.
    With quant="int8" the params are the ``quantize_serve_params`` layout
    (weight-only int8 + per-channel scales).
    """

    def prefill(params, tokens, k_pages, v_pages, write_page, write_off, lens):
        adt = jnp.dtype(cfg.dtype)
        b, t = tokens.shape
        hd, h, kh = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
        x = params["embed"].astype(adt)[tokens]  # [B,T,D]
        positions = jnp.arange(t)

        def block(x, xs):
            layer, kp, vp = xs
            h_in = model_lib._rms_norm(x, layer["attn_norm"], cfg.norm_eps)
            q = _proj(h_in, layer, "wq", adt, quant)
            k = _proj(h_in, layer, "wk", adt, quant)
            v = _proj(h_in, layer, "wv", adt, quant)
            q = q.reshape(b, t, h, hd)
            k = k.reshape(b, t, kh, hd)
            v = v.reshape(b, t, kh, hd)
            q = model_lib._rope(q, positions, cfg.rope_theta)
            k = model_lib._rope(k, positions, cfg.rope_theta)
            kp = kp.at[write_page, write_off].set(k.astype(kp.dtype), mode="drop")
            vp = vp.at[write_page, write_off].set(v.astype(vp.dtype), mode="drop")
            o = blockwise_attention(q, k, v, causal=True)
            o = o.astype(adt).reshape(b, t, h * hd)
            x = x + _proj(o, layer, "wo", adt, quant)
            h2 = model_lib._rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
            gate = _proj(h2, layer, "w_gate", adt, quant)
            up = _proj(h2, layer, "w_up", adt, quant)
            hidden = jax.nn.silu(gate.astype(jnp.float32)).astype(adt) * up
            return x + _proj(hidden, layer, "w_down", adt, quant), (kp, vp)

        layer_params = {key: params[key] for key in _serve_layer_keys(quant)}
        x, (k_pages, v_pages) = jax.lax.scan(
            block, x, (layer_params, k_pages, v_pages)
        )
        x = model_lib._rms_norm(x, params["final_norm"], cfg.norm_eps)
        last_idx = jnp.clip(lens - 1, 0, t - 1)
        last = x[jnp.arange(b), last_idx]  # [B, D]
        logits = _logits(last, params, adt, quant)
        out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if with_hidden:
            return out, last, k_pages, v_pages
        return out, k_pages, v_pages

    if mesh is None:
        return jax.jit(prefill, donate_argnums=(2, 3))
    param_sh, page_sh, rep = _serve_shardings(quant, mesh)
    out_sh = (
        (rep, rep, page_sh, page_sh) if with_hidden else (rep, page_sh, page_sh)
    )
    return jax.jit(
        prefill,
        donate_argnums=(2, 3),
        in_shardings=(param_sh, rep, page_sh, page_sh, rep, rep, rep),
        out_shardings=out_sh,
    )


@functools.lru_cache(maxsize=None)
def make_decode_fn(cfg: LlamaConfig, quant: str = "none",
                   decode_impl: str = "xla", mesh: Optional[Mesh] = None):
    """jit'd single-token decode over the paged cache (memoized on config +
    quant + resolved decode_impl + mesh):
    (params, last_tokens, positions, k_pages, v_pages, page_tables,
     write_page, write_off) -> (next_tokens, k_pages, v_pages).

    One query per slot: the last emitted token (position = tokens stored so
    far) has its K/V appended to the slot's current page, then attends over
    the slot's whole paged prefix. Inactive slots ride along with dropped
    writes and garbage-but-finite outputs (fixed [max_batch] shape = one
    compilation for the engine's whole life). decode_impl="pallas" runs the
    in-repo paged-attention kernel (kernels/paged.py) instead of the XLA
    gather — pages are DMA'd page-at-a-time instead of materializing every
    slot's padded KV window.
    """

    def decode(params, last_tokens, positions, k_pages, v_pages, page_tables,
               write_page, write_off):
        adt = jnp.dtype(cfg.dtype)
        s = last_tokens.shape[0]
        hd, h, kh = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
        x = params["embed"].astype(adt)[last_tokens]  # [S, D]

        def block(x, xs):
            layer, kp, vp = xs
            h_in = model_lib._rms_norm(x, layer["attn_norm"], cfg.norm_eps)
            q = _proj(h_in, layer, "wq", adt, quant)
            k = _proj(h_in, layer, "wk", adt, quant)
            v = _proj(h_in, layer, "wv", adt, quant)
            q = _rope_single(q.reshape(s, h, hd), positions, cfg.rope_theta)
            k = _rope_single(k.reshape(s, kh, hd), positions, cfg.rope_theta)
            v = v.reshape(s, kh, hd)
            kp = kp.at[write_page, write_off].set(k.astype(kp.dtype), mode="drop")
            vp = vp.at[write_page, write_off].set(v.astype(vp.dtype), mode="drop")
            if decode_impl == "pallas":
                o = paged_decode_attention_pallas(
                    q, kp, vp, page_tables, positions + 1
                )
            else:
                o = paged_decode_attention(q, kp, vp, page_tables, positions + 1)
            x = x + _proj(o.astype(adt).reshape(s, h * hd), layer, "wo", adt,
                          quant)
            h2 = model_lib._rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
            gate = _proj(h2, layer, "w_gate", adt, quant)
            up = _proj(h2, layer, "w_up", adt, quant)
            hidden = jax.nn.silu(gate.astype(jnp.float32)).astype(adt) * up
            return x + _proj(hidden, layer, "w_down", adt, quant), (kp, vp)

        layer_params = {key: params[key] for key in _serve_layer_keys(quant)}
        x, (k_pages, v_pages) = jax.lax.scan(
            block, x, (layer_params, k_pages, v_pages)
        )
        x = model_lib._rms_norm(x, params["final_norm"], cfg.norm_eps)
        logits = _logits(x, params, adt, quant)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), k_pages, v_pages

    if mesh is None:
        return jax.jit(decode, donate_argnums=(3, 4))
    param_sh, page_sh, rep = _serve_shardings(quant, mesh)
    return jax.jit(
        decode,
        donate_argnums=(3, 4),
        in_shardings=(param_sh, rep, rep, page_sh, page_sh, rep, rep, rep),
        out_shardings=(rep, page_sh, page_sh),
    )


def _rope_chunk(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding with per-row positions: x [S,C,H,D], positions [S,C]
    (each slot's chunk starts at its own absolute offset)."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [S, C, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


@functools.lru_cache(maxsize=None)
def make_chunk_fn(cfg: LlamaConfig, quant: str = "none",
                  decode_impl: str = "xla", emit: str = "last",
                  mesh: Optional[Mesh] = None, with_hidden: bool = False):
    """jit'd multi-token step over the paged cache — the shared program behind
    chunked prefill, prefix-cache suffix prefill, AND speculative verify:
    (params, tokens, starts, valid, k_pages, v_pages, page_tables,
     write_page, write_off) -> (next_tokens, k_pages, v_pages).

    tokens [S, C]: C consecutive tokens per slot, the first sitting at
    absolute position starts[s]; valid [S] counts real (non-pad) tokens.
    Each token's K/V is scattered into the slot's pages (write_page/write_off
    [S, C]; pool-sized index = dropped write for padding), then all C queries
    attend causally over the slot's paged prefix including the chunk itself
    (attention.paged_chunk_attention, or the Pallas twin when
    decode_impl="pallas") — decode is exactly the C == 1 special case.

    emit="last" returns [S] greedy tokens from each slot's LAST valid
    position (prefill: only the final chunk's emission is meaningful, and the
    lm_head runs on one position per slot, not the whole chunk);
    emit="all" returns [S, C] greedy tokens at EVERY position (speculative
    verify: position i's argmax is the model's true next token after
    consuming tokens[:, :i+1], which the host's accept/reject rule compares
    against the drafts).

    ``with_hidden`` (the draft-head engines) inserts the final hidden state
    after the tokens in the returns — [S, D] at the last valid position for
    emit="last" (the final prefill chunk seeds the head's first proposal),
    [S, C, D] at every position for emit="all" (the host picks the ACCEPTED
    position's hidden as the next proposal's conditioning — the hidden the
    target computed for exactly the tokens it ended up keeping).
    """

    def chunk_step(params, tokens, starts, valid, k_pages, v_pages,
                   page_tables, write_page, write_off):
        adt = jnp.dtype(cfg.dtype)
        s, c = tokens.shape
        hd, h, kh = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
        x = params["embed"].astype(adt)[tokens]  # [S, C, D]
        positions = starts[:, None] + jnp.arange(c)[None, :]  # [S, C]
        kv_lens = starts + valid

        def block(x, xs):
            layer, kp, vp = xs
            h_in = model_lib._rms_norm(x, layer["attn_norm"], cfg.norm_eps)
            q = _proj(h_in, layer, "wq", adt, quant).reshape(s, c, h, hd)
            k = _proj(h_in, layer, "wk", adt, quant).reshape(s, c, kh, hd)
            v = _proj(h_in, layer, "wv", adt, quant).reshape(s, c, kh, hd)
            q = _rope_chunk(q, positions, cfg.rope_theta)
            k = _rope_chunk(k, positions, cfg.rope_theta)
            kp = kp.at[write_page, write_off].set(k.astype(kp.dtype), mode="drop")
            vp = vp.at[write_page, write_off].set(v.astype(vp.dtype), mode="drop")
            if decode_impl == "pallas":
                o = paged_chunk_attention_pallas(
                    q, kp, vp, page_tables, starts, kv_lens
                )
            else:
                o = paged_chunk_attention(q, kp, vp, page_tables, starts)
            o = o.astype(adt).reshape(s, c, h * hd)
            x = x + _proj(o, layer, "wo", adt, quant)
            h2 = model_lib._rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
            gate = _proj(h2, layer, "w_gate", adt, quant)
            up = _proj(h2, layer, "w_up", adt, quant)
            hidden = jax.nn.silu(gate.astype(jnp.float32)).astype(adt) * up
            return x + _proj(hidden, layer, "w_down", adt, quant), (kp, vp)

        layer_params = {key: params[key] for key in _serve_layer_keys(quant)}
        x, (k_pages, v_pages) = jax.lax.scan(
            block, x, (layer_params, k_pages, v_pages)
        )
        x = model_lib._rms_norm(x, params["final_norm"], cfg.norm_eps)
        if emit == "last":
            last_idx = jnp.clip(valid - 1, 0, c - 1)
            hidden = x[jnp.arange(s), last_idx]  # [S, D]
            logits = _logits(hidden, params, adt, quant)
        else:
            hidden = x  # [S, C, D]
            logits = _logits(x, params, adt, quant)  # [S, C, V]
        out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if with_hidden:
            return out, hidden, k_pages, v_pages
        return out, k_pages, v_pages

    if mesh is None:
        return jax.jit(chunk_step, donate_argnums=(4, 5))
    param_sh, page_sh, rep = _serve_shardings(quant, mesh)
    out_sh = (
        (rep, rep, page_sh, page_sh) if with_hidden else (rep, page_sh, page_sh)
    )
    return jax.jit(
        chunk_step,
        donate_argnums=(4, 5),
        in_shardings=(param_sh, rep, rep, rep, page_sh, page_sh, rep, rep, rep),
        out_shardings=out_sh,
    )


@functools.lru_cache(maxsize=None)
def make_draft_fn(cfg: LlamaConfig, k: int, quant: str = "none",
                  mesh: Optional[Mesh] = None):
    """jit'd model-based draft proposer (the --spec-model replacement for the
    n-gram index): (params, draft, hidden, last_tokens) -> drafts [S, k]
    int32.

    One scan of the EAGLE-style head (model.draft_apply) proposes k tokens
    for every slot at once: each step embeds the previous token through the
    TARGET's embed table, applies the head to (hidden, embedding), and takes
    the argmax through the target's lm_head — quant-aware, so a weight-only
    int8/fp8 engine drafts through the same quantized lm_head its verify
    forward scores with. The head's own output hidden feeds the next step,
    exactly the rollout the distillation loss trained. Fixed [max_batch]
    shapes = one compile for the engine's life; inactive slots ride along on
    garbage inputs and their rows are ignored.

    On a serve mesh the head and its activations stay replicated; only the
    embed gather and the lm_head projection touch tp-sharded weights (GSPMD
    inserts the same vocab reduction the decode path pays)."""

    def propose(params, draft, hidden, last_tokens):
        adt = jnp.dtype(cfg.dtype)

        def step(carry, _):
            h, t = carry
            e = params["embed"].astype(adt)[t]  # [S, D]
            h2 = model_lib.draft_apply(draft, h, e, cfg)
            logits = _logits(h2, params, adt, quant)
            nt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (h2, nt), nt

        _, drafts = jax.lax.scan(
            step,
            (hidden.astype(adt), last_tokens.astype(jnp.int32)),
            None,
            length=k,
        )
        return jnp.swapaxes(drafts, 0, 1)  # [S, k]

    if mesh is None:
        return jax.jit(propose)
    param_sh, _, rep = _serve_shardings(quant, mesh)
    return jax.jit(
        propose,
        in_shardings=(param_sh, rep, rep, rep),
        out_shardings=rep,
    )


def propose_ngram_drafts(context: List[int], k: int, max_n: int = 3) -> List[int]:
    """Self-drafting n-gram proposer (prompt-lookup decoding): find the most
    recent earlier occurrence of the context's trailing n-gram (longest n
    first) and propose the k tokens that followed it. A miss proposes the
    last token repeated — a draft is only ever a THROUGHPUT bet; the verify
    step keeps the output token-identical to greedy no matter what is
    proposed."""
    if k <= 0 or not context:
        return []
    for n in range(min(max_n, len(context) - 1), 0, -1):
        pattern = context[-n:]
        # Most recent occurrence strictly before the trailing one.
        for i in range(len(context) - n - 1, -1, -1):
            if context[i:i + n] == pattern:
                drafts = context[i + n:i + n + k]
                if drafts:
                    return drafts + [context[-1]] * (k - len(drafts))
    return [context[-1]] * k


def _ngram_record(context: List[int], i: int, index: dict, max_n: int = 3):
    """Token context[i] just arrived: every n-gram ENDING at i-1 now has a
    continuation starting at i — record it (latest occurrence wins). Grams
    without a continuation are deliberately never recorded, which is what
    keeps lookups from matching the trailing gram against itself."""
    for n in range(1, max_n + 1):
        if n > i:
            break
        index[tuple(context[i - n:i])] = i


def _ngram_index(context: List[int], max_n: int = 3) -> dict:
    """Continuation index over a whole context (admission-time build; after
    that ``_ngram_record`` maintains it in O(max_n) per emitted token)."""
    index: dict = {}
    for i in range(1, len(context)):
        _ngram_record(context, i, index, max_n)
    return index


def propose_from_index(
    context: List[int], index: dict, k: int, max_n: int = 3
) -> List[int]:
    """O(max_n) drop-in for ``propose_ngram_drafts`` given its context's
    ``_ngram_index``: identical proposals (tested), without the O(context)
    backward scan per decoding slot per engine step — host work that would
    otherwise sit serialized against the device on the decode hot path."""
    if k <= 0 or not context:
        return []
    for n in range(min(max_n, len(context) - 1), 0, -1):
        pos = index.get(tuple(context[-n:]))
        if pos is not None:
            drafts = context[pos:pos + k]
            return drafts + [context[-1]] * (k - len(drafts))
    return [context[-1]] * k


class _CacheBlock:
    """One cached full page of KV: the block's hash-chain key, the page id it
    seals, how many live requests reference it, and an LRU stamp."""

    __slots__ = ("key", "page", "refs", "last_used")

    def __init__(self, key, page: int, refs: int, last_used: int) -> None:
        self.key = key
        self.page = page
        self.refs = refs
        self.last_used = last_used


class PrefixCache:
    """Refcounted registry of sealed full-page prompt blocks, keyed by a hash
    chain: block i's key is (parent_key, tuple(block_tokens)) — exact-match
    (no collision risk), and a prefix match is a walk down the chain.

    Invariants the tests pin:
    - a cached page is NEVER written again (registration happens only after
      the owning prefill fully filled it with prompt tokens, and generation
      always writes at positions past the prompt) — so sharing needs no
      copy-on-write: divergence just stops the match and the request
      allocates private pages from there;
    - a block with refs > 0 is never evicted (``evict`` only frees LRU blocks
      with refs == 0 and no cached children — a child's referents hold refs
      on every ancestor, so parents can't be freed under live children);
    - match() caps at len(prompt) - 1 tokens so prefill always has at least
      one position left to compute the first output token from.
    """

    def __init__(self, page_size: int) -> None:
        self.page_size = page_size
        self.blocks: Dict[tuple, _CacheBlock] = {}
        self._page_block: Dict[int, _CacheBlock] = {}
        self._children: Dict[tuple, int] = {}  # key -> cached child count
        self._clock = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self.blocks)

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, prompt: List[int]) -> Tuple[List[int], int]:
        """Longest cached prefix of `prompt` in whole blocks: returns
        (page_ids, matched_token_count) and takes a reference on every
        matched block (caller must ``release`` them on slot teardown, or
        immediately if admission fails)."""
        p = self.page_size
        max_blocks = (len(prompt) - 1) // p
        key: Optional[tuple] = None
        matched: List[_CacheBlock] = []
        for b in range(max_blocks):
            key = (key, tuple(prompt[b * p:(b + 1) * p]))
            blk = self.blocks.get(key)
            if blk is None:
                break
            matched.append(blk)
        stamp = self._tick()
        for blk in matched:
            blk.refs += 1
            blk.last_used = stamp
        return [blk.page for blk in matched], len(matched) * p

    def register(self, prompt: List[int], slot_pages: List[int]) -> None:
        """Seal the full prompt blocks of a just-completed prefill into the
        cache. slot_pages[i] is the page holding tokens [i*p, (i+1)*p). The
        owning request keeps using the page, so each new block starts at
        refs = 1; already-present keys are skipped (a concurrent duplicate
        prefill keeps its copy private — freed at release like any private
        page)."""
        p = self.page_size
        key: Optional[tuple] = None
        stamp = self._tick()
        for b in range(len(prompt) // p):
            key = (key, tuple(prompt[b * p:(b + 1) * p]))
            existing = self.blocks.get(key)
            if existing is not None:
                continue
            page = slot_pages[b]
            if page in self._page_block:
                # This position is served BY a cached page (a matched block):
                # nothing to register.
                continue
            self.blocks[key] = _CacheBlock(key, page, refs=1, last_used=stamp)
            self._page_block[page] = self.blocks[key]
            if key[0] is not None:
                self._children[key[0]] = self._children.get(key[0], 0) + 1

    def release(self, pages: List[int]) -> List[int]:
        """Drop one reference per cached page in `pages`; returns the subset
        that is NOT cached (truly private — the caller frees those). Cached
        pages stay resident at refs == 0 until evicted."""
        private: List[int] = []
        stamp = self._tick()
        for page in pages:
            blk = self._page_block.get(page)
            if blk is None:
                private.append(page)
            else:
                blk.refs -= 1
                blk.last_used = stamp
        return private

    def evictable_count(self) -> int:
        return sum(1 for blk in self.blocks.values() if blk.refs == 0)

    def evict(self, n: int) -> List[int]:
        """Free up to n pages from refs == 0 blocks, LRU first, leaves before
        parents (evicting a parent under a cached child would orphan the
        child's chain — and every ref-0 subtree always has a ref-0 leaf, so
        leaf-first eviction can always drain it)."""
        freed: List[int] = []
        while len(freed) < n:
            candidates = [
                blk for blk in self.blocks.values()
                if blk.refs == 0 and self._children.get(blk.key, 0) == 0
            ]
            if not candidates:
                break
            victim = min(candidates, key=lambda blk: blk.last_used)
            del self.blocks[victim.key]
            del self._page_block[victim.page]
            parent = victim.key[0]
            if parent is not None:
                self._children[parent] -= 1
                if not self._children[parent]:
                    del self._children[parent]
            freed.append(victim.page)
            self.evictions += 1
        return freed


def _bucket(n: int, lo: int = 8) -> int:
    """Smallest power of two >= n (min lo): bounds the number of distinct
    prefill shapes XLA ever compiles."""
    b = lo
    while b < n:
        b *= 2
    return b


# ---------------------------------------------------------------------------
# Request-level serving observability (ISSUE 18): stage histograms + a
# flight recorder of completed request traces. Everything here is host-side
# bookkeeping around the jitted calls — the device program is untouched, so
# the instrumented engine is token-identical to the uninstrumented one by
# construction (and tests/test_serve_observability.py asserts it).


# Histogram families the engine observes (labeled by replica; the step-stage
# family adds a `stage` label). Advertised cold on both the replica-local
# /metrics (create_serve_app) and the control plane's exposition
# (server/services/prometheus.py _HISTOGRAM_HELP).
SERVE_HISTOGRAM_HELP = {
    "dstack_tpu_serve_queue_wait_seconds":
        "Engine admission-queue wait (request enqueued -> slot admitted) by replica",
    "dstack_tpu_serve_prefill_seconds":
        "Prefill span (first prefill chunk launched -> first token) by replica",
    "dstack_tpu_serve_ttft_seconds":
        "Engine-side time-to-first-token (enqueued -> first token) by replica",
    "dstack_tpu_serve_itl_seconds":
        "Inter-token latency between consecutive generated tokens by replica",
    "dstack_tpu_serve_decode_tokens_per_s":
        "Per-request decode throughput (generated tokens over the decode span) by replica",
    "dstack_tpu_serve_step_stage_seconds":
        "Engine step time split by stage (admit/prefill/decode) by replica",
}


def _replica_label() -> str:
    """Stable identity of this serving replica for metric labels: the
    orchestrator's replica env when running under the agent, host rank as a
    fallback, "0" for bare/test engines."""
    return (
        os.environ.get("DSTACK_TPU_REPLICA")
        or os.environ.get("DSTACK_NODE_RANK")
        or "0"
    )


class FlightRecorder:
    """Bounded ring buffer of completed request traces (the per-request
    "flight recorder"): the last N completions, plus a separate same-sized
    ring for requests slower than a threshold so a burst of fast requests
    can't evict the slow trace an operator is hunting. Queryable via the
    replica's GET /debug/traces and fleet-wide through the control plane
    (`dstack-tpu trace <run>`)."""

    def __init__(
        self,
        capacity: Optional[int] = None,
        slow_threshold: Optional[float] = None,
    ) -> None:
        if capacity is None:
            capacity = int(os.environ.get("DSTACK_TPU_FLIGHT_RECORDER_SIZE", "128"))
        if slow_threshold is None:
            slow_threshold = float(
                os.environ.get("DSTACK_TPU_FLIGHT_SLOW_SECONDS", "2.0")
            )
        self.capacity = max(int(capacity), 1)
        self.slow_threshold = float(slow_threshold)
        self._recent: Deque[dict] = collections.deque(maxlen=self.capacity)
        self._slow: Deque[dict] = collections.deque(maxlen=self.capacity)
        self._seq = 0
        self._lock = threading.Lock()

    def record(self, trace: dict) -> None:
        with self._lock:
            self._seq += 1
            trace = dict(trace, seq=self._seq)
            trace["slow"] = trace.get("total_s", 0.0) >= self.slow_threshold
            self._recent.append(trace)
            if trace["slow"]:
                self._slow.append(trace)

    def snapshot(
        self,
        request_id: Optional[str] = None,
        trace_id: Optional[str] = None,
        limit: Optional[int] = None,
    ) -> List[dict]:
        """Newest-first merged view (recent ring + retained slow traces,
        deduplicated), optionally filtered by request id or trace id."""
        with self._lock:
            merged = {t["seq"]: t for t in self._slow}
            merged.update({t["seq"]: t for t in self._recent})
        out = [merged[s] for s in sorted(merged, reverse=True)]
        if request_id is not None:
            out = [t for t in out if t.get("req_id") == request_id]
        if trace_id is not None:
            out = [t for t in out if t.get("trace_id") == trace_id]
        if limit is not None:
            out = out[: max(int(limit), 0)]
        return out

    def latency_summary(self) -> dict:
        """TTFT/ITL p50/p99 (ms) over the recent ring — the engine telemetry
        point's serving-latency fields (`dstack-tpu top` columns)."""
        with self._lock:
            records = list(self._recent)
        ttfts = sorted(
            t["ttft_s"] for t in records if t.get("ttft_s") is not None
        )
        itls = sorted(
            ms / 1000.0 for t in records for ms in (t.get("itl_ms") or ())
        )
        from dstack_tpu.utils.common import nearest_rank

        out: dict = {}
        if ttfts:
            out["ttft_p50_ms"] = round(nearest_rank(ttfts, 0.50) * 1000, 2)
            out["ttft_p99_ms"] = round(nearest_rank(ttfts, 0.99) * 1000, 2)
        if itls:
            out["itl_p50_ms"] = round(nearest_rank(itls, 0.50) * 1000, 2)
            out["itl_p99_ms"] = round(nearest_rank(itls, 0.99) * 1000, 2)
        return out


class ServeEngine:
    """Host-side continuous-batching loop over the jitted prefill/decode fns.

    Not thread-safe except for ``submit``/gauge reads (``EngineRunner`` is the
    one caller of ``step``). All scheduling state — free pages, page tables,
    slot occupancy — lives on the host; the device only ever sees fixed-shape
    batches, so the engine compiles one decode program plus a handful of
    bucketed prefill shapes.
    """

    def __init__(
        self,
        cfg: LlamaConfig,
        engine_cfg: Optional[EngineConfig] = None,
        params: Optional[dict] = None,
        seed: int = 0,
        mesh: Optional[Mesh] = None,
        draft_params: Optional[dict] = None,
    ) -> None:
        self.cfg = cfg
        self.ecfg = engine_cfg or EngineConfig()
        self.mesh = mesh
        if mesh is not None:
            sharding_lib.validate_serve_mesh(cfg, mesh)
        if self.ecfg.policy not in ("continuous", "static"):
            raise ValueError(f"unknown scheduling policy {self.ecfg.policy!r}")
        if self.ecfg.decode_impl not in DECODE_IMPLS:
            raise ValueError(
                f"unknown decode_impl {self.ecfg.decode_impl!r}; expected one"
                f" of {DECODE_IMPLS}"
            )
        if self.ecfg.prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0 (0 = whole-prompt prefill), got"
                f" {self.ecfg.prefill_chunk}"
            )
        if self.ecfg.spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0 (0 = one token per step), got"
                f" {self.ecfg.spec_tokens}"
            )
        if self.ecfg.prefix_cache and self.ecfg.num_pages < 2:
            raise ValueError(
                "prefix_cache needs a page pool of at least 2 (one cacheable"
                " block plus the active tail)"
            )
        quant_lib.check_quant(self.ecfg.quant)
        self.params = params if params is not None else model_lib.init_params(
            cfg, jax.random.PRNGKey(seed)
        )
        # Weight-only int8/fp8: quantize once at engine build; the jitted fns
        # see only the quantized layout. The fp originals are released —
        # keeping them would hold bf16/fp32 weights in HBM *alongside* the
        # quantized copy, inverting the memory win. Reference decoders keep
        # their own tree.
        quant = self.ecfg.quant
        if quant_lib.is_weight_only(quant):
            if self.params is not None and "lm_head_q" in self.params:
                # Already in the weight-only layout (load_serve_params
                # quantized leaf-by-leaf as it consumed the restored fp tree)
                # — re-quantizing quantized values would be wrong AND the fp
                # originals are gone by design.
                self._serve_params = self.params
            else:
                self._serve_params = quantize_serve_params(self.params, mode=quant)
            self.params = None
        else:
            self._serve_params = self.params
        if mesh is not None:
            # Pin the weights to the serve layout up front: device_put is a
            # no-op for leaves already laid out right (load_serve_params
            # restores directly into these shardings), a one-time reshard for
            # host/meshless trees.
            shardings = sharding_lib.serve_param_sharding(mesh, quant)
            self._serve_params = {
                k: jax.device_put(v, shardings[k])
                for k, v in self._serve_params.items()
            }
        # Model-based drafting: the head proposes from the target's last
        # hidden state, so every forward that can advance a slot's position
        # (prefill, chunk prefill, verify) must also hand that hidden back.
        # with_hidden=False keeps the n-gram-only engine byte-identical.
        if draft_params is not None and self.ecfg.spec_tokens <= 0:
            raise ValueError(
                "draft_params given but spec_tokens == 0 — the draft head"
                " only proposes inside speculative decode (--spec-tokens k)"
            )
        self._use_draft = draft_params is not None
        self.draft_params = draft_params
        if self._use_draft and mesh is not None:
            rep = NamedSharding(mesh, P())
            self.draft_params = {
                k: jax.device_put(v, rep) for k, v in draft_params.items()
            }
        self.decode_impl = resolve_decode_impl(self.ecfg.decode_impl)
        self._prefill_fn = make_prefill_fn(
            cfg, quant, mesh, with_hidden=self._use_draft
        )
        self._decode_fn = make_decode_fn(cfg, quant, self.decode_impl, mesh)
        # Tier-2 prefill (chunked and/or cache-hit suffix) replaces the
        # whole-prompt prefill path; with both features off the tier-1 path
        # runs unchanged.
        self._tier2_prefill = (
            self.ecfg.prefill_chunk > 0 or self.ecfg.prefix_cache
        )
        if self._tier2_prefill:
            self._chunk_fn = make_chunk_fn(
                cfg, quant, self.decode_impl, "last", mesh,
                with_hidden=self._use_draft,
            )
        if self.ecfg.spec_tokens > 0:
            self._verify_fn = make_chunk_fn(
                cfg, quant, self.decode_impl, "all", mesh,
                with_hidden=self._use_draft,
            )
        if self._use_draft:
            self._draft_fn = make_draft_fn(
                cfg, self.ecfg.spec_tokens, quant, mesh
            )
        self._cache = (
            PrefixCache(self.ecfg.page_size) if self.ecfg.prefix_cache else None
        )

        page, pool = self.ecfg.page_size, self.ecfg.num_pages
        max_seq = self.ecfg.max_seq or cfg.max_seq_len
        self.max_seq = max_seq
        self.table_width = -(-max_seq // page)  # pages per sequence, ceil
        shape = (cfg.n_layers, pool, page, cfg.n_kv_heads, cfg.head_dim)
        cache_dtype = jnp.dtype(cfg.dtype)
        if mesh is not None:
            page_sharding = NamedSharding(mesh, sharding_lib.SERVE_PAGE_SPEC)
            self.k_pages = jax.device_put(
                jnp.zeros(shape, cache_dtype), page_sharding
            )
            self.v_pages = jax.device_put(
                jnp.zeros(shape, cache_dtype), page_sharding
            )
        else:
            self.k_pages = jnp.zeros(shape, cache_dtype)
            self.v_pages = jnp.zeros(shape, cache_dtype)

        self._free: List[int] = list(range(pool))
        mb = self.ecfg.max_batch
        self.page_tables = np.zeros((mb, self.table_width), np.int32)
        self.seq_lens = np.zeros(mb, np.int64)       # KV positions stored
        self.last_tokens = np.zeros(mb, np.int32)    # last emitted token
        # Per-slot target hidden state behind last_tokens — what the draft
        # head conditions on. Refreshed by prefill and by every verify step
        # (the hidden at the accept boundary); stale rows are harmless
        # because a slot's row is rewritten before its next proposal.
        if self._use_draft:
            self.last_hidden = np.zeros((mb, cfg.d_model), np.float32)
        self.slots: List[Optional[GenRequest]] = [None] * mb
        self.slot_pages: List[List[int]] = [[] for _ in range(mb)]

        self.pending: Deque[GenRequest] = collections.deque()
        self._lock = threading.Lock()
        self._req_counter = 0
        # Observability: the metric label identifying this replica, and the
        # ring buffer of completed request traces (GET /debug/traces).
        self.replica = _replica_label()
        self.flight = FlightRecorder()
        # Cumulative counters for /stats and bench extras.
        self.total_steps = 0
        self.total_tokens = 0
        self.total_finished = 0
        self.total_preemptions = 0
        self.total_prefix_lookup_tokens = 0  # prompt tokens through admission
        self.total_prefix_hit_tokens = 0     # of those, served from the cache
        self.total_spec_proposed = 0         # draft tokens sent to verify
        self.total_spec_accepted = 0         # of those, accepted
        self.total_spec_fallbacks = 0        # slots switched draft -> n-gram
        # Sliding window of per-slot-per-step (proposed, accepted) samples
        # behind spec_accept_rate_windowed (satellite: lifetime averages mask
        # a proposer that has gone cold on current traffic).
        self._spec_recent: Deque[Tuple[int, int]] = collections.deque(
            maxlen=max(self.ecfg.spec_window, 1)
        )

    # -- submission (thread-safe) -----------------------------------------

    def submit(
        self,
        prompt_tokens: List[int],
        max_new_tokens: Optional[int] = None,
        eos_id: Optional[int] = None,
        req_id: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> GenRequest:
        if not prompt_tokens:
            raise ValueError("empty prompt")
        max_new = max_new_tokens or self.ecfg.max_new_default
        if len(prompt_tokens) + max_new > self.max_seq:
            raise ValueError(
                f"prompt ({len(prompt_tokens)}) + max_new_tokens ({max_new})"
                f" exceeds the engine's max_seq {self.max_seq}"
            )
        need = -(-(len(prompt_tokens) + max_new) // self.ecfg.page_size)
        if need > self.ecfg.num_pages:
            raise ValueError("request larger than the whole page pool")
        with self._lock:
            if req_id is None:
                self._req_counter += 1
                req_id = f"req-{self._req_counter}"
            req = GenRequest(
                req_id=req_id,
                prompt=list(prompt_tokens),
                max_new_tokens=max_new,
                eos_id=eos_id if eos_id is not None else self.ecfg.eos_id,
                submitted_t=time.monotonic(),
                trace_id=trace_id or tracing.current_trace_id(),
            )
            self.pending.append(req)
        return req

    # -- gauges ------------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self.pending)

    @property
    def active_count(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def has_work(self) -> bool:
        return bool(self.pending) or self.active_count > 0

    @property
    def available_pages(self) -> int:
        """Pages the allocator can produce right now: the free list plus
        refs == 0 cache blocks it may evict."""
        n = len(self._free)
        if self._cache is not None:
            n += self._cache.evictable_count()
        return n

    @property
    def mesh_desc(self) -> str:
        """"ddNxtpM" for a sharded engine, "" for the meshless one."""
        if self.mesh is None:
            return ""
        shape = dict(self.mesh.shape)
        return f"dd{shape.get('dd', 1)}xtp{shape.get('tp', 1)}"

    @property
    def prefix_hit_rate(self) -> float:
        """Fraction of admitted prompt tokens served from the prefix cache."""
        return self.total_prefix_hit_tokens / max(
            self.total_prefix_lookup_tokens, 1
        )

    @property
    def spec_accept_rate(self) -> float:
        """Fraction of proposed draft tokens the verify step accepted."""
        return self.total_spec_accepted / max(self.total_spec_proposed, 1)

    @property
    def spec_accept_rate_windowed(self) -> float:
        """Accept rate over the last spec_window spec steps — what the
        proposer is doing NOW, where the lifetime average dilutes a cold
        streak with history. Before any spec step it mirrors the lifetime
        rate (0.0), so gauges render from the first scrape."""
        proposed = sum(p for p, _ in self._spec_recent)
        if proposed == 0:
            return 0.0
        return sum(a for _, a in self._spec_recent) / proposed

    def stats(self) -> Dict[str, float]:
        return {
            "queue_depth": self.queue_depth,
            "active": self.active_count,
            "free_pages": self.free_pages,
            "available_pages": self.available_pages,
            "total_pages": self.ecfg.num_pages,
            "max_batch": self.ecfg.max_batch,
            "steps": self.total_steps,
            "generated_tokens": self.total_tokens,
            "finished_requests": self.total_finished,
            "preemptions": self.total_preemptions,
            "policy": self.ecfg.policy,
            "decode_impl": self.decode_impl,
            "quant": self.ecfg.quant,
            "mesh": self.mesh_desc,
            "prefill_chunk": self.ecfg.prefill_chunk,
            "prefix_cache": int(self.ecfg.prefix_cache),
            "spec_tokens": self.ecfg.spec_tokens,
            "cached_pages": len(self._cache) if self._cache else 0,
            "prefix_evictions": self._cache.evictions if self._cache else 0,
            "prefix_hit_rate": round(self.prefix_hit_rate, 4),
            "spec_accept_rate": round(self.spec_accept_rate, 4),
            "spec_accept_rate_windowed": round(
                self.spec_accept_rate_windowed, 4
            ),
            "spec_proposer": "draft" if self._use_draft else "ngram",
            "spec_fallbacks": self.total_spec_fallbacks,
        }

    # -- the step loop -----------------------------------------------------

    def step(self) -> List[TokenEvent]:
        """One engine iteration: admit -> prefill (whole-prompt, or one chunk
        per mid-prefill slot in tier 2) -> one decode step (single-token, or
        draft+verify with spec_tokens). Returns the tokens emitted this step,
        in emission order."""
        events: List[TokenEvent] = []
        # Step-stage attribution (host wall time; the np.asarray conversions
        # inside each _run_* force a device sync, so these spans are honest).
        # Idle stages are not observed — an all-decode steady state must not
        # bury the prefill distribution under zero-length samples.
        labels = {"replica": self.replica}
        t0 = time.monotonic()
        admitted = self._admit()
        t_admit = time.monotonic()
        if admitted:
            tracing.observe(
                "dstack_tpu_serve_step_stage_seconds", t_admit - t0,
                {**labels, "stage": "admit"},
            )
        prefilled = False
        if not self._tier2_prefill:
            if admitted:
                self._run_prefill(admitted, events)
                prefilled = True
        elif any(self._prefilling(s) for s in range(self.ecfg.max_batch)):
            self._run_chunk_prefill(events)
            prefilled = True
        t_prefill = time.monotonic()
        if prefilled:
            tracing.observe(
                "dstack_tpu_serve_step_stage_seconds", t_prefill - t_admit,
                {**labels, "stage": "prefill"},
            )
        decoding = [
            s for s, r in enumerate(self.slots)
            if r is not None and not self._prefilling(s)
        ]
        if decoding:
            if self.ecfg.spec_tokens > 0:
                self._run_spec_decode(decoding, events)
            else:
                self._run_decode(decoding, events)
            tracing.observe(
                "dstack_tpu_serve_step_stage_seconds",
                time.monotonic() - t_prefill, {**labels, "stage": "decode"},
            )
        self.total_steps += 1
        return events

    def _prefilling(self, slot: int) -> bool:
        req = self.slots[slot]
        return req is not None and req.pos < len(req.prompt)

    def _pages_for(self, tokens: int) -> int:
        return -(-tokens // self.ecfg.page_size)

    def _try_alloc(self, n: int) -> Optional[List[int]]:
        """Pop n pages from the free list, evicting LRU refs == 0 cache
        blocks to refill it if needed; None (nothing taken, nothing evicted)
        when the pool genuinely can't produce n pages. Eviction only runs
        when it can actually satisfy the request: a failed allocation leaves
        its caller blocked either way, so destroying cached prefixes for it
        would cost every future sharer a re-prefill and buy nothing."""
        if len(self._free) < n and self._cache is not None:
            if len(self._free) + self._cache.evictable_count() >= n:
                self._free.extend(self._cache.evict(n - len(self._free)))
        if len(self._free) < n:
            return None
        return [self._free.pop() for _ in range(n)]

    def _admit(self) -> List[Tuple[int, GenRequest]]:
        """Move queued requests into free slots (FIFO, head-of-line blocking
        when pages are short — admission order is completion-signal order).
        Static policy: only admit into an EMPTY batch. With the prefix cache
        on, the prompt's longest cached block-prefix arrives as shared pages
        and only the suffix needs fresh ones (and a prefill pass)."""
        if self.ecfg.policy == "static" and self.active_count:
            return []
        admitted: List[Tuple[int, GenRequest]] = []
        free_slots = [i for i, r in enumerate(self.slots) if r is None]
        while free_slots:
            with self._lock:
                if not self.pending:
                    break
                req = self.pending[0]
                shared_pages: List[int] = []
                matched = 0
                if self._cache is not None:
                    shared_pages, matched = self._cache.match(req.prompt)
                # Reserve the prompt plus one decode page of headroom; growth
                # beyond that allocates on demand (preempting if dry).
                need = self._pages_for(len(req.prompt) + 1) - len(shared_pages)
                new_pages = self._try_alloc(need)
                if new_pages is None:
                    if shared_pages:  # roll back the match refs
                        self._free.extend(self._cache.release(shared_pages))
                    break
                self.pending.popleft()
            slot = free_slots.pop(0)
            pages = shared_pages + new_pages
            self.slot_pages[slot] = pages
            row = self.page_tables[slot]
            row[:] = 0
            row[: len(pages)] = pages
            self.seq_lens[slot] = matched
            req.pos = matched
            req.cached_tokens = matched
            if req.preemptions == 0:
                # A preemption resume re-matches its OWN sealed blocks —
                # counting that as a hit (and the resume prompt as fresh
                # lookups) would inflate the exported hit ratio exactly when
                # the pool is under pressure and the gauge matters most.
                self.total_prefix_lookup_tokens += len(req.prompt)
                self.total_prefix_hit_tokens += matched
            if req.admitted_t == 0.0:
                # First admission only: a preemption re-admission is decode
                # backpressure, not queue wait.
                req.admitted_t = time.monotonic()
                tracing.observe(
                    "dstack_tpu_serve_queue_wait_seconds",
                    req.admitted_t - req.submitted_t,
                    {"replica": self.replica},
                )
            self.slots[slot] = req
            admitted.append((slot, req))
        return admitted

    def _run_prefill(
        self, admitted: List[Tuple[int, GenRequest]], events: List[TokenEvent]
    ) -> None:
        page = self.ecfg.page_size
        pool = self.ecfg.num_pages
        now = time.monotonic()
        for _, req in admitted:
            if req.prefill_start_t == 0.0:
                req.prefill_start_t = now
        t_pad = _bucket(max(len(req.prompt) for _, req in admitted))
        b_pad = _bucket(len(admitted), lo=1)
        tokens = np.zeros((b_pad, t_pad), np.int32)
        lens = np.zeros(b_pad, np.int32)
        # pool-sized page index = out-of-bounds = dropped write: padding (and
        # padded batch rows) never lands in the cache.
        write_page = np.full((b_pad, t_pad), pool, np.int32)
        write_off = np.zeros((b_pad, t_pad), np.int32)
        for i, (slot, req) in enumerate(admitted):
            n = len(req.prompt)
            tokens[i, :n] = req.prompt
            lens[i] = n
            pos = np.arange(n)
            pages = np.asarray(self.slot_pages[slot], np.int32)
            write_page[i, :n] = pages[pos // page]
            write_off[i, :n] = pos % page

        out = self._prefill_fn(
            self._serve_params, jnp.asarray(tokens), self.k_pages, self.v_pages,
            jnp.asarray(write_page), jnp.asarray(write_off), jnp.asarray(lens),
        )
        if self._use_draft:
            next_tokens, hidden, self.k_pages, self.v_pages = out
            hidden = np.asarray(hidden, np.float32)
        else:
            next_tokens, self.k_pages, self.v_pages = out
        next_tokens = np.asarray(next_tokens)
        for i, (slot, req) in enumerate(admitted):
            self.seq_lens[slot] = len(req.prompt)
            req.pos = len(req.prompt)
            if self._use_draft:
                self.last_hidden[slot] = hidden[i]
            self._emit(slot, req, int(next_tokens[i]), events)

    def _run_chunk_prefill(self, events: List[TokenEvent]) -> None:
        """Advance every mid-prefill slot by one chunk (tier-2 prefill). The
        chunk's K/V is scattered into the slot's pages and its queries attend
        over the paged prefix — so a cache-hit suffix resumes mid-prompt and
        a long prompt spreads over many steps, at most prefill_chunk tokens
        each. The final chunk's last-position argmax is the request's first
        generated token."""
        page = self.ecfg.page_size
        pool = self.ecfg.num_pages
        slots = [s for s in range(self.ecfg.max_batch) if self._prefilling(s)]
        if not slots:
            return
        remaining = {
            s: len(self.slots[s].prompt) - self.slots[s].pos for s in slots
        }
        chunk = self.ecfg.prefill_chunk or _bucket(max(remaining.values()), lo=8)
        s_pad = _bucket(len(slots), lo=1)
        tokens = np.zeros((s_pad, chunk), np.int32)
        starts = np.zeros(s_pad, np.int32)
        valid = np.zeros(s_pad, np.int32)
        write_page = np.full((s_pad, chunk), pool, np.int32)
        write_off = np.zeros((s_pad, chunk), np.int32)
        tables = np.zeros((s_pad, self.table_width), np.int32)
        now = time.monotonic()
        for i, slot in enumerate(slots):
            req = self.slots[slot]
            if req.prefill_start_t == 0.0:
                req.prefill_start_t = now  # first chunk of this request
            n = min(chunk, remaining[slot])
            tokens[i, :n] = req.prompt[req.pos:req.pos + n]
            starts[i] = req.pos
            valid[i] = n
            pos = req.pos + np.arange(n)
            pages = np.asarray(self.slot_pages[slot], np.int32)
            write_page[i, :n] = pages[pos // page]
            write_off[i, :n] = pos % page
            tables[i] = self.page_tables[slot]

        out = self._chunk_fn(
            self._serve_params, jnp.asarray(tokens), jnp.asarray(starts),
            jnp.asarray(valid), self.k_pages, self.v_pages,
            jnp.asarray(tables), jnp.asarray(write_page),
            jnp.asarray(write_off),
        )
        if self._use_draft:
            next_tokens, hidden, self.k_pages, self.v_pages = out
            hidden = np.asarray(hidden, np.float32)
        else:
            next_tokens, self.k_pages, self.v_pages = out
        next_tokens = np.asarray(next_tokens)
        for i, slot in enumerate(slots):
            req = self.slots[slot]
            req.pos += int(valid[i])
            self.seq_lens[slot] = req.pos
            if req.pos < len(req.prompt):
                continue  # more chunks to go; nothing emitted yet
            if self._cache is not None:
                self._cache.register(req.prompt, self.slot_pages[slot])
            if self._use_draft:
                # The final chunk's last valid position is the prompt's last
                # token — exactly the state the head conditions on next.
                self.last_hidden[slot] = hidden[i]
            self._emit(slot, req, int(next_tokens[i]), events)

    def _run_decode(self, decoding: List[int], events: List[TokenEvent]) -> None:
        page = self.ecfg.page_size
        pool = self.ecfg.num_pages
        mb = self.ecfg.max_batch
        self._ensure_decode_pages(decoding)
        write_page = np.full(mb, pool, np.int32)
        write_off = np.zeros(mb, np.int32)
        active = []
        for slot in decoding:
            if self.slots[slot] is None:  # preempted by _ensure_decode_pages
                continue
            pos = int(self.seq_lens[slot])
            write_page[slot] = self.page_tables[slot, pos // page]
            write_off[slot] = pos % page
            active.append(slot)
        if not active:
            return

        next_tokens, self.k_pages, self.v_pages = self._decode_fn(
            self._serve_params,
            jnp.asarray(self.last_tokens),
            jnp.asarray(self.seq_lens, dtype=jnp.int32),
            self.k_pages,
            self.v_pages,
            jnp.asarray(self.page_tables),
            jnp.asarray(write_page),
            jnp.asarray(write_off),
        )
        next_tokens = np.asarray(next_tokens)
        for slot in active:
            req = self.slots[slot]
            self.seq_lens[slot] += 1  # the last token's KV just landed
            self._emit(slot, req, int(next_tokens[slot]), events)

    def _run_spec_decode(
        self, decoding: List[int], events: List[TokenEvent]
    ) -> None:
        """Draft + verify decode step: each slot's row is [last_token,
        d1..dk] at positions seq_len..seq_len+k; one chunk forward scores all
        of them, and position i's argmax is the model's true next token after
        consuming the row's first i+1 tokens. Greedy accept runs left to
        right: draft d_{i+1} is accepted iff it equals argmax_i; the first
        mismatch emits the correction instead. Every emitted token is exactly
        what single-token greedy decode would have produced — speculation
        only changes how many land per step. Rejected positions' K/V stays in
        the pages but is never read: seq_len advances only past accepted
        tokens, and the next step re-writes those positions before attending."""
        page = self.ecfg.page_size
        pool = self.ecfg.num_pages
        mb = self.ecfg.max_batch
        c = self.ecfg.spec_tokens + 1
        # Clip each slot's row to the tokens it can still emit: emitted per
        # step <= valid, and submit() guarantees prompt + max_new <= max_seq,
        # so seq_len + valid never crosses the page-table width either.
        valid = np.zeros(mb, np.int32)
        for slot in decoding:
            req = self.slots[slot]
            valid[slot] = min(c, req.max_new_tokens - len(req.tokens))
        self._ensure_decode_pages(decoding, extra=valid)
        # Model-based drafting: one fixed-shape jitted forward proposes for
        # every slot at once from the target's last hidden state (rows of
        # preempted or fallen-back slots are computed but ignored — batching
        # the head beats per-slot dispatch, and shapes stay compile-stable).
        draft_rows = None
        if self._use_draft and any(
            self.slots[s] is not None and self.slots[s].draft_ok
            for s in decoding
        ):
            draft_rows = np.asarray(self._draft_fn(
                self._serve_params, self.draft_params,
                jnp.asarray(self.last_hidden),
                jnp.asarray(self.last_tokens),
            ))  # [mb, k] int32
        tokens = np.zeros((mb, c), np.int32)
        starts = np.zeros(mb, np.int32)
        write_page = np.full((mb, c), pool, np.int32)
        write_off = np.zeros((mb, c), np.int32)
        active = []
        drafts: Dict[int, List[int]] = {}
        used_draft: Dict[int, bool] = {}
        for slot in decoding:
            req = self.slots[slot]
            if req is None:  # preempted by _ensure_decode_pages
                continue
            n = int(valid[slot])
            row = [int(self.last_tokens[slot])]
            if n > 1:
                if draft_rows is not None and req.draft_ok:
                    row += [int(t) for t in draft_rows[slot, : n - 1]]
                    used_draft[slot] = True
                else:
                    if req.spec_ctx is None:
                        # prompt + tokens[absorbed:] is the emitted stream
                        # with each token exactly once (plain prompt + tokens
                        # would duplicate the pre-preemption segment a refold
                        # already folded into the prompt).
                        req.spec_ctx = (
                            list(req.prompt) + list(req.tokens[req.absorbed:])
                        )
                        req.spec_index = _ngram_index(req.spec_ctx)
                    row += propose_from_index(
                        req.spec_ctx, req.spec_index, n - 1
                    )
            drafts[slot] = row[1:]
            tokens[slot, :n] = row
            starts[slot] = self.seq_lens[slot]
            pos = int(self.seq_lens[slot]) + np.arange(n)
            pages = np.asarray(self.slot_pages[slot], np.int32)
            write_page[slot, :n] = pages[pos // page]
            write_off[slot, :n] = pos % page
            active.append(slot)
        if not active:
            return

        out = self._verify_fn(
            self._serve_params, jnp.asarray(tokens),
            jnp.asarray(starts), jnp.asarray(valid, dtype=jnp.int32),
            self.k_pages, self.v_pages, jnp.asarray(self.page_tables),
            jnp.asarray(write_page), jnp.asarray(write_off),
        )
        if self._use_draft:
            out_tokens, hidden, self.k_pages, self.v_pages = out
            hidden = np.asarray(hidden, np.float32)  # [mb, c, d_model]
        else:
            out_tokens, self.k_pages, self.v_pages = out
        out_tokens = np.asarray(out_tokens)  # [mb, c]
        for slot in active:
            req = self.slots[slot]
            n = int(valid[slot])
            row_drafts = drafts[slot]
            accepted = 0
            while (
                accepted < n - 1
                and row_drafts[accepted] == int(out_tokens[slot, accepted])
            ):
                accepted += 1
            emitted = row_drafts[:accepted] + [int(out_tokens[slot, accepted])]
            self.total_spec_proposed += n - 1
            self.total_spec_accepted += accepted
            req.spec_proposed += n - 1
            req.spec_accepted += accepted
            self._spec_recent.append((n - 1, accepted))
            if self._use_draft:
                # Row position `accepted` is the target's state after
                # consuming every token it actually kept — the conditioning
                # for this slot's next proposal.
                self.last_hidden[slot] = hidden[slot, accepted]
                if used_draft.get(slot):
                    self._track_draft_accept(req, n - 1, accepted)
            # The accepted context tokens' K/V (row positions 0..accepted)
            # just landed; the new emitted tail token is not yet written.
            self.seq_lens[slot] += accepted + 1
            for token in emitted:
                self._emit(slot, req, token, events)
                if req.done:
                    break

    def _track_draft_accept(
        self, req: GenRequest, proposed: int, accepted: int
    ) -> None:
        """Per-request windowed accept tracking behind the automatic draft ->
        n-gram fallback. The window must be FULL before the rate is judged —
        a head that opens with a few unlucky steps on a hard prefix gets the
        whole window to recover — and the fallback is permanent for the
        request: flapping between proposers would churn the n-gram index for
        no benefit. threshold <= 0 disables fallback entirely."""
        if self.ecfg.spec_fallback_threshold <= 0:
            return
        if req.spec_recent is None:
            req.spec_recent = collections.deque(
                maxlen=max(self.ecfg.spec_fallback_window, 1)
            )
        req.spec_recent.append((proposed, accepted))
        if len(req.spec_recent) < (req.spec_recent.maxlen or 1):
            return
        total_p = sum(p for p, _ in req.spec_recent)
        total_a = sum(a for _, a in req.spec_recent)
        if total_p > 0 and total_a / total_p < self.ecfg.spec_fallback_threshold:
            req.draft_ok = False
            self.total_spec_fallbacks += 1

    def _ensure_decode_pages(
        self, decoding: List[int], extra: Optional[np.ndarray] = None
    ) -> None:
        """Every decoding slot about to write position seq_len (through
        seq_len + extra - 1 under speculation) needs those positions' pages
        allocated; a dry pool — free list AND evictable cache blocks —
        preempts the youngest request (fewest generated tokens) back to the
        queue: its pages fund the older requests, and it re-prefills later
        from prompt + generated so no emitted token is ever lost."""
        page = self.ecfg.page_size
        for slot in decoding:
            if self.slots[slot] is None:
                continue
            last_pos = int(self.seq_lens[slot])
            if extra is not None:
                last_pos += max(int(extra[slot]) - 1, 0)
            need_idx = last_pos // page
            while need_idx >= len(self.slot_pages[slot]):
                got = self._try_alloc(1)
                if got is None:
                    victim = self._pick_victim(exclude=slot)
                    if victim is None:
                        # Nothing to steal from: this slot itself is the
                        # youngest; requeue it.
                        self._preempt(slot)
                        break
                    self._preempt(victim)
                    continue
                self.slot_pages[slot].extend(got)
                self.page_tables[slot, len(self.slot_pages[slot]) - 1] = got[0]
            # If this slot was itself preempted, move on.

    def _pick_victim(self, exclude: int) -> Optional[int]:
        candidates = [
            (len(req.tokens), slot)
            for slot, req in enumerate(self.slots)
            if req is not None and slot != exclude
        ]
        if not candidates:
            return None
        return min(candidates)[1]

    def _preempt(self, slot: int) -> None:
        req = self.slots[slot]
        logger.info(
            "engine: preempting %s (%d generated) — page pool dry",
            req.req_id, len(req.tokens),
        )
        req.preemptions += 1
        self.total_preemptions += 1
        # Resume prompt carries everything decoded so far (but each generated
        # token exactly once, however many times this request is preempted);
        # re-admission prefills it and the next emitted token is genuinely new.
        req.prompt = req.prompt + req.tokens[req.absorbed:]
        req.absorbed = len(req.tokens)
        self._release_slot(slot)
        with self._lock:
            self.pending.appendleft(req)

    def _emit(
        self, slot: int, req: GenRequest, token: int, events: List[TokenEvent]
    ) -> None:
        req.tokens.append(token)
        now = time.monotonic()
        req.token_times.append(now)
        labels = {"replica": self.replica}
        if len(req.tokens) == 1:
            # First generated token = prefill done: TTFT and the prefill span
            # land here (a chunked prefill's span covers all its chunks).
            req.first_token_t = now
            tracing.observe(
                "dstack_tpu_serve_ttft_seconds", now - req.submitted_t, labels
            )
            if req.prefill_start_t:
                tracing.observe(
                    "dstack_tpu_serve_prefill_seconds",
                    now - req.prefill_start_t, labels,
                )
        else:
            tracing.observe(
                "dstack_tpu_serve_itl_seconds",
                now - req.token_times[-2], labels,
            )
        if req.spec_ctx is not None:
            req.spec_ctx.append(token)
            _ngram_record(req.spec_ctx, len(req.spec_ctx) - 1, req.spec_index)
        self.total_tokens += 1
        done = (
            len(req.tokens) >= req.max_new_tokens
            or (req.eos_id is not None and token == req.eos_id)
        )
        events.append(TokenEvent(req.req_id, token, len(req.tokens) - 1, done))
        if done:
            req.done = True
            req.finished_t = now
            self.total_finished += 1
            decode_s = now - req.first_token_t
            if len(req.tokens) > 1 and decode_s > 0:
                tracing.observe(
                    "dstack_tpu_serve_decode_tokens_per_s",
                    (len(req.tokens) - 1) / decode_s, labels,
                )
            self.flight.record(self._request_trace(req))
            self._release_slot(slot)
        else:
            self.last_tokens[slot] = token

    def _request_trace(self, req: GenRequest) -> dict:
        """The flight-recorder record for a completed request: stage spans as
        relative durations (monotonic stamps mean nothing across processes),
        per-token gaps, and the per-request cache/spec attribution."""
        ttft = req.first_token_t - req.submitted_t
        return {
            "req_id": req.req_id,
            "trace_id": req.trace_id,
            "replica": self.replica,
            "finished_at": time.time(),
            "queue_wait_s": round(req.admitted_t - req.submitted_t, 6),
            "prefill_s": round(
                req.first_token_t - req.prefill_start_t, 6
            ) if req.prefill_start_t else 0.0,
            "ttft_s": round(ttft, 6),
            "decode_s": round(req.finished_t - req.first_token_t, 6),
            "total_s": round(req.finished_t - req.submitted_t, 6),
            # Original prompt length: preemption refolds append generated
            # tokens to `prompt`, but exactly `absorbed` of them.
            "prompt_tokens": len(req.prompt) - req.absorbed,
            "cached_tokens": req.cached_tokens,
            "tokens": len(req.tokens),
            "preemptions": req.preemptions,
            "spec_proposed": req.spec_proposed,
            "spec_accepted": req.spec_accepted,
            "itl_ms": [
                round((b - a) * 1000, 3)
                for a, b in zip(req.token_times, req.token_times[1:])
            ],
        }

    def _release_slot(self, slot: int) -> None:
        if self._cache is not None:
            # Cached pages stay resident at refs == 0 (LRU-evictable); only
            # truly private pages return to the free list.
            self._free.extend(self._cache.release(self.slot_pages[slot]))
        else:
            self._free.extend(self.slot_pages[slot])
        self.slot_pages[slot] = []
        self.page_tables[slot, :] = 0
        self.seq_lens[slot] = 0
        self.last_tokens[slot] = 0
        self.slots[slot] = None


# ---------------------------------------------------------------------------
# Reference decoding (tests): full-context greedy decode, no cache.


def greedy_reference_decode(
    params: dict,
    cfg: LlamaConfig,
    prompt: List[int],
    max_new_tokens: int,
    eos_id: Optional[int] = None,
) -> List[int]:
    """O(T^2) greedy decode re-running the full forward per token — the
    ground truth the paged engine must match token for token."""
    toks = list(prompt)
    out: List[int] = []
    for _ in range(max_new_tokens):
        logits = model_lib.forward(params, jnp.asarray([toks], jnp.int32), cfg)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        if eos_id is not None and nxt == eos_id:
            break
        toks.append(nxt)
    return out


# ---------------------------------------------------------------------------
# Byte-level "tokenizer" for the HTTP surface: the engine serves synthetic
# weights, so the contract is tokens in/tokens out; text is a convenience.


def encode_text(text: str, vocab_size: int) -> List[int]:
    return [b % vocab_size for b in text.encode("utf-8")] or [0]


def decode_token(token: int) -> str:
    return chr(token) if 0x20 <= token < 0x7F else ""


# ---------------------------------------------------------------------------
# Engine thread + aiohttp app (the runnable service behind the proxy).


class EngineRunner(threading.Thread):
    """Owns the step loop on a background thread; bridges token events into
    per-request asyncio queues on the server's event loop. JAX compute blocks,
    so it must not run on the event loop — the classic host-scheduling/device-
    step overlap: while the device decodes, the loop streams tokens out."""

    def __init__(self, engine: ServeEngine, idle_wait: float = 0.05) -> None:
        super().__init__(name="serve-engine", daemon=True)
        self.engine = engine
        self.idle_wait = idle_wait
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._subs: Dict[str, Callable[[TokenEvent], None]] = {}
        self._subs_lock = threading.Lock()
        self._sub_counter = 0
        # Workload telemetry (no-op unless the runner agent exported
        # DSTACK_TPU_TELEMETRY_PATH): one `engine` point per second-ish while
        # stepping, so queue depth / hit rates reach the control plane even
        # when no request ever touches the proxy headers.
        from dstack_tpu.workloads import telemetry as telemetry_lib

        self._telemetry = telemetry_lib.get_emitter()
        self._telemetry_interval = 1.0
        self._last_telemetry = 0.0
        if self._telemetry.enabled:
            self._telemetry.mark(
                "run_start", workload="serve",
                max_batch=engine.ecfg.max_batch, policy=engine.ecfg.policy,
            )
        # contextvars don't cross thread boundaries: capture the constructing
        # context (trace id included) so the step loop's spans and logs join
        # the trace that started the engine instead of an anonymous one.
        self._step_loop_in_ctx = tracing.wrap_with_context(self._step_loop)

    def submit(
        self,
        prompt_tokens: List[int],
        max_new_tokens: Optional[int],
        on_event: Callable[[TokenEvent], None],
        trace_id: Optional[str] = None,
    ) -> GenRequest:
        """Register a per-token callback (invoked on the ENGINE thread; wrap
        with loop.call_soon_threadsafe for asyncio consumers) and enqueue.
        The callback is registered BEFORE the engine sees the request — the
        step loop runs on another thread and could otherwise emit the first
        token into the void."""
        with self._subs_lock:
            self._sub_counter += 1
            req_id = f"http-{self._sub_counter}"
            self._subs[req_id] = on_event
        try:
            req = self.engine.submit(
                prompt_tokens, max_new_tokens, req_id=req_id, trace_id=trace_id
            )
        except Exception:
            with self._subs_lock:
                self._subs.pop(req_id, None)
            raise
        self._wake.set()
        return req

    def step_once(self) -> None:
        """One engine step + event dispatch (run()'s body; tests gate on it)."""
        try:
            events = self.engine.step()
        except Exception:
            logger.exception("engine step failed")
            return
        if self._telemetry.enabled:
            now = time.monotonic()
            if now - self._last_telemetry >= self._telemetry_interval:
                self._last_telemetry = now
                s = self.engine.stats()
                self._telemetry.emit(
                    "engine",
                    queue_depth=s["queue_depth"],
                    active=s["active"],
                    free_pages=s["free_pages"],
                    generated_tokens=s["generated_tokens"],
                    finished_requests=s["finished_requests"],
                    preemptions=s["preemptions"],
                    prefix_hit_rate=s["prefix_hit_rate"],
                    spec_accept_rate=s["spec_accept_rate"],
                    # Serving-latency quantiles over the flight-recorder
                    # window — `dstack-tpu top`'s TTFT/ITL columns.
                    **self.engine.flight.latency_summary(),
                )
        for ev in events:
            with self._subs_lock:
                callback = self._subs.get(ev.req_id)
                if ev.done and callback is not None:
                    del self._subs[ev.req_id]
            if callback is not None:
                try:
                    callback(ev)
                except Exception:
                    logger.exception("token subscriber failed")

    def run(self) -> None:
        self._step_loop_in_ctx()

    def _step_loop(self) -> None:
        while not self._stop.is_set():
            if not self.engine.has_work():
                self._wake.wait(self.idle_wait)
                self._wake.clear()
                continue
            self.step_once()

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()


def create_serve_app(runner: EngineRunner):
    """aiohttp app: POST /generate (SSE token stream or buffered JSON),
    GET /stats (engine gauges — what the autoscaler's queue-depth signal
    reads), GET /health. Every response carries X-Dstack-Queue-Depth so the
    in-server proxy can record engine backlog without a single extra hop."""
    import asyncio

    from aiohttp import web

    engine = runner.engine

    def qd_headers() -> dict:
        headers = {"X-Dstack-Queue-Depth": str(engine.queue_depth)}
        # Tier-2 gauges ride the same channel as the queue depth: the proxy
        # records them in-memory and /metrics renders them per service, with
        # zero extra hops (services/proxy.py ENGINE_GAUGE_HEADERS).
        if engine.ecfg.prefix_cache:
            headers["X-Dstack-Prefix-Hit-Rate"] = (
                f"{engine.prefix_hit_rate:.4f}"
            )
        if engine.ecfg.spec_tokens > 0:
            # Windowed, not lifetime: the proxy gauge is a health signal, and
            # recent behavior is what fallback/tuning decisions look at.
            headers["X-Dstack-Spec-Accept-Rate"] = (
                f"{engine.spec_accept_rate_windowed:.4f}"
            )
        return headers

    async def generate(request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
        except ValueError:
            raise web.HTTPBadRequest(text="body must be JSON")
        tokens = body.get("prompt_tokens")
        if tokens is None:
            tokens = encode_text(str(body.get("prompt", "")), engine.cfg.vocab_size)
        if not isinstance(tokens, list) or not all(
            isinstance(t, int) and 0 <= t < engine.cfg.vocab_size for t in tokens
        ):
            raise web.HTTPBadRequest(text="prompt_tokens must be valid token ids")
        max_new = body.get("max_tokens")
        if max_new is not None and (
            not isinstance(max_new, int) or isinstance(max_new, bool)
            or max_new < 1
        ):
            raise web.HTTPBadRequest(text="max_tokens must be a positive integer")
        stream = bool(body.get("stream", True))

        # Adopt the caller's trace (the proxy stamps X-Dstack-Trace-Id on every
        # forwarded request) or mint one, so the engine's flight-recorder entry
        # for this request is joinable to the proxy-side latency record.
        trace_id = request.headers.get(tracing.TRACE_HEADER)
        if trace_id:
            tracing.set_trace_id(trace_id)
        else:
            trace_id = tracing.new_trace()

        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()

        def on_event(ev: TokenEvent) -> None:
            loop.call_soon_threadsafe(queue.put_nowait, ev)

        try:
            req = runner.submit(tokens, max_new, on_event, trace_id=trace_id)
        except ValueError as e:
            raise web.HTTPBadRequest(text=str(e))

        if not stream:
            out: List[int] = []
            while True:
                ev = await queue.get()
                out.append(ev.token)
                if ev.done:
                    break
            return web.json_response(
                {
                    "tokens": out,
                    "text": "".join(decode_token(t) for t in out),
                    "request_id": req.req_id,
                    "trace_id": trace_id,
                },
                headers={**qd_headers(), tracing.TRACE_HEADER: trace_id},
            )

        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-store",
                tracing.TRACE_HEADER: trace_id,
                **qd_headers(),
            }
        )
        await resp.prepare(request)
        # Nothing is written until the first token lands: the first SSE chunk
        # through the proxy IS time-to-first-token.
        while True:
            ev = await queue.get()
            payload = {"token": ev.token, "index": ev.index,
                       "text": decode_token(ev.token)}
            await resp.write(b"data: " + json.dumps(payload).encode() + b"\n\n")
            if ev.done:
                break
        await resp.write(b"data: [DONE]\n\n")
        await resp.write_eof()
        return resp

    async def stats(request: web.Request) -> web.Response:
        return web.json_response(engine.stats(), headers=qd_headers())

    async def health(request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"}, headers=qd_headers())

    async def debug_traces(request: web.Request) -> web.Response:
        """Flight-recorder readout: last-N completed request traces plus the
        slow-request ring, filterable by request or trace id. The proxy fans
        this out fleet-wide (services/proxy.py collect_service_traces)."""
        limit_q = request.query.get("limit")
        try:
            limit = int(limit_q) if limit_q else None
        except ValueError:
            raise web.HTTPBadRequest(text="limit must be an integer")
        traces = engine.flight.snapshot(
            request_id=request.query.get("request") or None,
            trace_id=request.query.get("trace") or None,
            limit=limit,
        )
        return web.json_response(
            {
                "replica": engine.replica,
                "capacity": engine.flight.capacity,
                "slow_threshold_s": engine.flight.slow_threshold,
                "traces": traces,
            },
            headers=qd_headers(),
        )

    async def metrics(request: web.Request) -> web.Response:
        # Replica-local Prometheus surface: the engine runs in its own process,
        # so the control plane's /metrics can't see this registry directly.
        return web.Response(
            text=tracing.render_exposition(SERVE_HISTOGRAM_HELP),
            content_type="text/plain",
            headers=qd_headers(),
        )

    app = web.Application()
    app.router.add_post("/generate", generate)
    app.router.add_get("/stats", stats)
    app.router.add_get("/health", health)
    app.router.add_get("/debug/traces", debug_traces)
    app.router.add_get("/metrics", metrics)
    return app


def main() -> None:
    """``python -m dstack_tpu.workloads.serve`` — the runnable serving
    entrypoint (examples/serve-llama.dstack.yml). Binds DSTACK_SERVICE_PORT
    (the control plane's contract) unless --port says otherwise."""
    import argparse
    import os

    from aiohttp import web

    from dstack_tpu.workloads import xla_flags
    from dstack_tpu.workloads.config import PRESETS

    applied = xla_flags.apply()
    if applied:
        print(f"overlap XLA defaults applied: {applied['XLA_FLAGS']}", flush=True)

    parser = argparse.ArgumentParser(prog="dstack_tpu.workloads.serve")
    parser.add_argument("--config", default="test", choices=sorted(PRESETS))
    parser.add_argument("--port", type=int,
                        default=int(os.environ.get("DSTACK_SERVICE_PORT", "8000")))
    parser.add_argument("--page-size", type=int, default=16)
    parser.add_argument("--pages", type=int, default=512,
                        help="KV page pool size (per layer)")
    parser.add_argument("--max-batch", type=int, default=8,
                        help="decode slots (max in-flight sequences)")
    parser.add_argument("--max-new", type=int, default=64,
                        help="default max_tokens when a request names none")
    parser.add_argument("--policy", default="continuous",
                        choices=["continuous", "static"])
    parser.add_argument("--decode-impl", default="auto", dest="decode_impl",
                        choices=list(DECODE_IMPLS),
                        help="decode attention: auto = Pallas paged kernel on"
                             " TPU, XLA gather elsewhere")
    parser.add_argument("--quant", default="none",
                        choices=["none", "int8", "fp8"],
                        help="int8/fp8 = weight-only quantization (projection"
                             " weights stored int8 + per-channel scales —"
                             " half the weight HBM)")
    parser.add_argument("--prefill-chunk", type=int, default=0,
                        dest="prefill_chunk",
                        help="max prompt tokens prefilled per engine step"
                             " (0 = whole prompt at once); chunking keeps one"
                             " long prompt from stalling the decode batch")
    parser.add_argument("--prefix-cache", action="store_true",
                        dest="prefix_cache",
                        help="reuse KV pages across requests sharing a prompt"
                             " prefix (refcounted, LRU-evicted full blocks)")
    parser.add_argument("--spec-tokens", type=int, default=0,
                        dest="spec_tokens",
                        help="speculative decode: n-gram draft tokens"
                             " verified per step (0 = off); output stays"
                             " token-identical to greedy")
    parser.add_argument("--spec-model", default="", dest="spec_model",
                        help="checkpoint dir holding a distilled draft head"
                             " (train.py --draft-head, .draft subtree);"
                             " replaces the n-gram proposer for --spec-tokens"
                             " — output stays token-identical to greedy")
    parser.add_argument("--spec-model-step", type=int, default=None,
                        dest="spec_model_step",
                        help="draft-head checkpoint step (default: latest"
                             " complete)")
    parser.add_argument("--spec-fallback-window", type=int, default=16,
                        dest="spec_fallback_window",
                        help="spec steps per request the accept-rate fallback"
                             " judges over (window must fill first)")
    parser.add_argument("--spec-fallback-threshold", type=float, default=0.1,
                        dest="spec_fallback_threshold",
                        help="windowed accept rate below which a slot falls"
                             " back from the draft head to the n-gram"
                             " proposer (<= 0 disables fallback)")
    parser.add_argument("--checkpoint-dir", default="", dest="checkpoint_dir",
                        help="restore real weights from a train checkpoint"
                             " (CheckpointManager layout; the .params subtree"
                             " of a TrainState or a params-only tree) instead"
                             " of serving synthetic init")
    parser.add_argument("--checkpoint-step", type=int, default=None,
                        dest="checkpoint_step",
                        help="checkpoint step to restore (default: latest"
                             " complete)")
    parser.add_argument("--mesh", default="",
                        help="serve mesh spec: tpN shards projections/heads"
                             " over N chips (ddMxtpN adds an explicit replica"
                             " axis; default meshless — one chip per replica)")
    args = parser.parse_args()

    cfg = get_config(args.config)
    mesh = parse_mesh_arg(args.mesh)
    params = None
    restored = None
    if args.checkpoint_dir:
        params, restored = load_serve_params(
            args.checkpoint_dir, cfg, mesh=mesh, quant=args.quant,
            step=args.checkpoint_step,
        )
        print(
            f"restored checkpoint step {restored['step']} from"
            f" {args.checkpoint_dir}"
            + (f" (saved on mesh {restored['mesh']})" if restored.get("mesh")
               else ""),
            flush=True,
        )
    draft_params = None
    if args.spec_model:
        if args.spec_tokens <= 0:
            raise SystemExit("--spec-model needs --spec-tokens > 0")
        draft_params, draft_manifest = load_draft_params(
            args.spec_model, cfg, mesh=mesh, step=args.spec_model_step,
        )
        print(
            f"draft head restored from {args.spec_model} step"
            f" {draft_manifest['step']} (.draft subtree)",
            flush=True,
        )
    engine = ServeEngine(
        cfg,
        EngineConfig(
            page_size=args.page_size,
            num_pages=args.pages,
            max_batch=args.max_batch,
            max_new_default=args.max_new,
            policy=args.policy,
            decode_impl=args.decode_impl,
            quant=args.quant,
            prefill_chunk=args.prefill_chunk,
            prefix_cache=args.prefix_cache,
            spec_tokens=args.spec_tokens,
            spec_fallback_window=args.spec_fallback_window,
            spec_fallback_threshold=args.spec_fallback_threshold,
        ),
        params=params,
        mesh=mesh,
        draft_params=draft_params,
    )
    runner = EngineRunner(engine)
    runner.start()
    print(
        f"serving config={args.config} on :{args.port} "
        f"(pages={args.pages}x{args.page_size}, slots={args.max_batch}, "
        f"policy={args.policy}, decode={engine.decode_impl}, "
        f"quant={args.quant}, prefill_chunk={args.prefill_chunk}, "
        f"prefix_cache={args.prefix_cache}, spec_tokens={args.spec_tokens}, "
        f"spec_proposer={'draft' if draft_params is not None else 'ngram'}, "
        f"mesh={engine.mesh_desc or 'none'}, "
        f"weights={'checkpoint' if args.checkpoint_dir else 'synthetic'})",
        flush=True,
    )
    try:
        web.run_app(create_serve_app(runner), host="0.0.0.0", port=args.port,
                    print=None)
    finally:
        runner.shutdown()


if __name__ == "__main__":
    main()
