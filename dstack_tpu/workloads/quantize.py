"""Int8 + fp8 quantized matmuls for train (STE) and serve (weight-only).

Two regimes, one scale scheme (per-channel absmax, symmetric, no zero point —
the TPU-friendly layout: scales broadcast along lanes, the MXU runs the int8
dot natively with int32 accumulation):

- **Dynamic int8 for training** (``int8_matmul_ste``): both operands are
  quantized on the fly — activations per row (over the contraction dim),
  weights per output channel — the dot runs int8×int8→int32, and the result
  is rescaled in fp32. The custom VJP is a straight-through estimator: the
  backward pass uses the ORIGINAL fp operands, so gradients flow exactly as
  in the fp step and the quantization noise acts as forward-only
  regularization. This is what makes the tiny-config convergence test ("int8
  not worse") meaningful.
- **Weight-only int8 for serving** (``quantize_weight`` +
  ``weight_only_matmul``): weights are quantized ONCE at engine build
  (halving their HBM vs bf16, the usual serve bottleneck), dequantized on the
  fly into the activation dtype, and the matmul accumulates in fp32. No
  activation quantization — decode batches are small, so the matmul is
  bandwidth-bound on weights and the fp activation path keeps greedy-decode
  drift minimal.

**fp8 (e4m3/e5m2)** reuses the same per-channel absmax scheme: operands are
scaled into the fp8 dtype's dynamic range and CAST (the cast is the rounding
— fp8 is a float grid, not an integer one), the dot accumulates in fp32, and
the scales factor back out exactly. On v5p+ the MXU runs the fp8 dot
natively (~2x the bf16 rate); older generations upcast in hardware, so
``quant=fp8`` is gated to v5p+ at ``config.validate_config`` time — CPU
interpret/test runs are allowed everywhere (identical numerics, no
throughput claim). e4m3 (max 448, 3 mantissa bits) is the default: matmul
operands want precision over range; e5m2 exists for the gradient-like tails.

Everything is expressed over the one matmul shape the model uses after
``lax.scan`` unstacks the layer axis: ``x[..., K] @ w[K, N]``.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

INT8_MAX = 127.0

# fp8 representable maxima (jnp.finfo): the absmax scale maps each channel's
# peak onto these, so the cast never overflows to inf.
FP8_MAX = {"e4m3": 448.0, "e5m2": 57344.0}
FP8_DTYPES = {"e4m3": jnp.float8_e4m3fn, "e5m2": jnp.float8_e5m2}
FP8_DEFAULT_FORMAT = "e4m3"


class QuantizedWeight(NamedTuple):
    """int8/fp8 values + fp32 per-output-channel scales (shape [..., 1, N] so
    a stacked [L, K, N] weight carries [L, 1, N] scales that slice cleanly
    under scan)."""

    values: jax.Array  # int8 or float8_*
    scales: jax.Array  # float32


def _absmax(x: jax.Array, axis: int, max_val: float) -> jax.Array:
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    s = s / max_val
    return jnp.where(s == 0.0, 1.0, s)


def absmax_scales(x: jax.Array, axis: int) -> jax.Array:
    """Symmetric per-channel int8 scales over ``axis`` (fp32, keepdims). Zero
    channels get scale 1 so dequantization never divides by zero."""
    return _absmax(x, axis, INT8_MAX)


def quantize_int8(x: jax.Array, axis: int) -> Tuple[jax.Array, jax.Array]:
    """(int8 values, fp32 keepdims scales); round-to-nearest-even, clipped."""
    scales = absmax_scales(x, axis)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scales), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), scales


def dequantize(values: jax.Array, scales: jax.Array) -> jax.Array:
    return values.astype(jnp.float32) * scales


def quantize_fp8(
    x: jax.Array, axis: int, fmt: str = FP8_DEFAULT_FORMAT
) -> Tuple[jax.Array, jax.Array]:
    """(fp8 values, fp32 keepdims scales). The cast IS the rounding: each
    channel is scaled so its absmax lands on the format's representable max,
    then cast to the fp8 dtype (round-to-nearest-even in hardware)."""
    scales = _absmax(x, axis, FP8_MAX[fmt])
    q = (x.astype(jnp.float32) / scales).astype(FP8_DTYPES[fmt])
    return q, scales


def quantize_weight(
    w: jax.Array, axis: int = -2, mode: str = "int8"
) -> QuantizedWeight:
    """Per-output-channel weight quantization; ``axis`` is the contraction
    dim (default: second-to-last, i.e. K of [..., K, N]). ``mode`` picks the
    grid: "int8" (default) or "fp8" (e4m3 — serve weights want mantissa)."""
    if mode == "fp8":
        values, scales = quantize_fp8(w, axis)
    else:
        values, scales = quantize_int8(w, axis)
    return QuantizedWeight(values, scales)


def int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """Dynamically-quantized ``x[..., K] @ w[K, N]`` -> fp32.

    Activations: per-row scales (each [..., K] row quantized over K).
    Weights: per-output-channel scales (each column over K). The dot itself is
    int8×int8 with int32 accumulation (``preferred_element_type`` routes it to
    the MXU's native int8 path on TPU); both scales factor out exactly, so the
    only error is the rounding of the operands.
    """
    xq, xs = quantize_int8(x, axis=-1)   # xs [..., 1]
    wq, ws = quantize_int8(w, axis=0)    # ws [1, N]
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * xs * ws


@jax.custom_vjp
def int8_matmul_ste(x: jax.Array, w: jax.Array) -> jax.Array:
    """int8_matmul with straight-through gradients (train path)."""
    return int8_matmul(x, w)


def _ste_fwd(x, w):
    return int8_matmul(x, w), (x, w)


def _ste_bwd(res, g):
    # Straight-through: differentiate y = x @ w as if no quantization
    # happened, against the ORIGINAL operands. g is fp32 [..., N].
    x, w = res
    dx = jax.lax.dot_general(
        g, w.astype(jnp.float32), (((g.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    gf = g.reshape(-1, g.shape[-1])
    xf = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    dw = jax.lax.dot_general(
        xf, gf, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_matmul_ste.defvjp(_ste_fwd, _ste_bwd)


def fp8_matmul(
    x: jax.Array, w: jax.Array, fmt: str = FP8_DEFAULT_FORMAT
) -> jax.Array:
    """Dynamically-quantized fp8 ``x[..., K] @ w[K, N]`` -> fp32.

    Same scale algebra as ``int8_matmul`` (activations per row, weights per
    output channel); the dot runs on the fp8 operands with fp32 accumulation
    — ``preferred_element_type`` routes it to the native fp8 MXU path on
    v5p+, and CPU jaxlib emulates the identical numerics for tests."""
    xq, xs = quantize_fp8(x, axis=-1, fmt=fmt)   # xs [..., 1]
    wq, ws = quantize_fp8(w, axis=0, fmt=fmt)    # ws [1, N]
    acc = jax.lax.dot_general(
        xq, wq, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return acc * xs * ws


@jax.custom_vjp
def fp8_matmul_ste(x: jax.Array, w: jax.Array) -> jax.Array:
    """fp8_matmul with straight-through gradients (train path): forward in
    e4m3, backward the EXACT fp gradients against the original operands —
    the same contract as ``int8_matmul_ste``, shared ``_ste_bwd``."""
    return fp8_matmul(x, w)


def _fp8_ste_fwd(x, w):
    return fp8_matmul(x, w), (x, w)


fp8_matmul_ste.defvjp(_fp8_ste_fwd, _ste_bwd)


def weight_only_matmul(
    x: jax.Array,          # [..., K] activation dtype
    values: jax.Array,     # [K, N] int8 or fp8
    scales: jax.Array,     # [1, N] fp32
) -> jax.Array:
    """Serve path: dequantize-on-use, fp32 accumulation; returns fp32.
    Dtype-agnostic over the value grid — int8 and fp8 weights take the same
    path (``values.astype`` is the dequantize-to-activation-dtype step)."""
    w = values.astype(x.dtype)
    acc = jax.lax.dot_general(
        x, w, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return acc * scales


def fake_quant(w: jax.Array, axis: int) -> jax.Array:
    """Quantize-dequantize with straight-through gradients — the einsum-shaped
    escape hatch for weights ``int8_matmul`` can't express (the MoE per-expert
    [E, D, F] tensors): numerics are int8-grid exact, accumulation stays fp.
    """
    values, scales = quantize_int8(w, axis)
    deq = (values.astype(jnp.float32) * scales).astype(w.dtype)
    return w + jax.lax.stop_gradient(deq - w)


def matmul(x: jax.Array, w: jax.Array, quant: str, adt=None) -> jax.Array:
    """The model-side dispatch: ``x[..., K] @ w[K, N]`` under the config's
    ``quant`` mode, returned in ``adt`` (default: x.dtype). ``w`` is the fp
    master weight — serve's pre-quantized path uses ``weight_only_matmul``
    directly."""
    adt = adt or x.dtype
    if quant == "int8":
        return int8_matmul_ste(x, w).astype(adt)
    if quant == "fp8":
        return fp8_matmul_ste(x, w).astype(adt)
    out = jax.lax.dot_general(
        x, w.astype(adt), (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out.astype(adt)


QUANT_MODES = ("none", "int8", "fp8")

# The quant modes whose serve path pre-quantizes weights once at engine build
# (quantize_serve_params) and dequantizes on use (weight_only_matmul).
WEIGHT_ONLY_MODES = ("int8", "fp8")


def is_weight_only(quant: str) -> bool:
    return quant in WEIGHT_ONLY_MODES


def check_quant(quant: str) -> None:
    if quant not in QUANT_MODES:
        raise ValueError(
            f"unknown quant mode {quant!r}; expected one of {QUANT_MODES}"
        )
