"""Backend identity + config wire models (parity: reference core/models/backends.py)."""

from __future__ import annotations

from enum import Enum
from typing import List, Optional

from dstack_tpu.core.models.common import CoreModel


class BackendType(str, Enum):
    """Cloud drivers shipped with the framework.

    The reference ships 16 GPU-centric backends; this build is TPU-first: GCP (the only
    cloud with TPUs), local (dev/test, shim-less), remote (SSH fleets of TPU VMs), and
    mock (testing). The Compute ABC keeps the same extension surface so more clouds can
    be added (reference base/compute.py:52-367).
    """

    GCP = "gcp"
    LOCAL = "local"
    REMOTE = "remote"
    MOCK = "mock"


class BackendConfig(CoreModel):
    type: BackendType
    project_id: Optional[str] = None  # GCP project
    regions: Optional[List[str]] = None
    creds: Optional[dict] = None

    def masked(self) -> "BackendConfig":
        c = self.model_copy(deep=True)
        if c.creds:
            c.creds = {k: "******" for k in c.creds}
        return c


class BackendInfo(CoreModel):
    name: str
    config: BackendConfig
