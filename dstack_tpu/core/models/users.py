"""User/project wire models (parity: reference core/models/{users,projects}.py)."""

from __future__ import annotations

import datetime
import uuid
from enum import Enum
from typing import List, Optional

from pydantic import Field

from dstack_tpu.core.models.common import CoreModel


class GlobalRole(str, Enum):
    ADMIN = "admin"
    USER = "user"


class ProjectRole(str, Enum):
    ADMIN = "admin"
    MANAGER = "manager"
    USER = "user"


class User(CoreModel):
    id: uuid.UUID
    username: str
    global_role: GlobalRole = GlobalRole.USER
    email: Optional[str] = None
    active: bool = True
    created_at: Optional[datetime.datetime] = None


class UserWithCreds(User):
    creds: Optional[dict] = None  # {"token": "..."}


class Member(CoreModel):
    user: User
    project_role: ProjectRole


class Project(CoreModel):
    id: uuid.UUID
    project_name: str
    owner: User
    created_at: Optional[datetime.datetime] = None
    members: List[Member] = Field(default_factory=list)
    is_public: bool = False
