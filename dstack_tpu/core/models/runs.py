"""Run/job wire models + the run/job state machines.

Parity: /root/reference src/dstack/_internal/core/models/runs.py (JobStatus:44,
JobTerminationReason:104, RunStatus:474, JobSpec:185, JobProvisioningData:209,
ClusterInfo:270, Run:492, RunPlan:576). The cluster contract is re-designed for TPU:
`ClusterInfo` carries slice topology + JAX coordinator + MegaScale env instead of an MPI
hostfile (reference runner executor.go:262-274)."""

from __future__ import annotations

import datetime
import uuid
from enum import Enum
from typing import Dict, List, Optional

from pydantic import Field

from dstack_tpu.core.models.common import CoreModel, RegistryAuth
from dstack_tpu.core.models.configurations import AnyRunConfiguration
from dstack_tpu.core.models.instances import InstanceType, SSHConnectionParams
from dstack_tpu.core.models.profiles import Profile, RetryPolicy, UtilizationPolicy
from dstack_tpu.core.models.resources import ResourcesSpec
from dstack_tpu.core.models.services import ServiceSpec


class JobStatus(str, Enum):
    SUBMITTED = "submitted"
    PROVISIONING = "provisioning"
    PULLING = "pulling"
    RUNNING = "running"
    TERMINATING = "terminating"
    TERMINATED = "terminated"
    ABORTED = "aborted"
    FAILED = "failed"
    DONE = "done"

    @classmethod
    def finished_statuses(cls) -> List["JobStatus"]:
        return [cls.TERMINATED, cls.ABORTED, cls.FAILED, cls.DONE]

    def is_finished(self) -> bool:
        return self in self.finished_statuses()


class JobTerminationReason(str, Enum):
    # set by the server
    FAILED_TO_START_DUE_TO_NO_CAPACITY = "failed_to_start_due_to_no_capacity"
    INTERRUPTED_BY_NO_CAPACITY = "interrupted_by_no_capacity"
    INSTANCE_UNREACHABLE = "instance_unreachable"
    WAITING_INSTANCE_LIMIT_EXCEEDED = "waiting_instance_limit_exceeded"
    TERMINATED_BY_USER = "terminated_by_user"
    VOLUME_ERROR = "volume_error"
    GATEWAY_ERROR = "gateway_error"
    SCALED_DOWN = "scaled_down"
    DONE_BY_RUNNER = "done_by_runner"
    ABORTED_BY_USER = "aborted_by_user"
    TERMINATED_BY_SERVER = "terminated_by_server"
    INACTIVITY_DURATION_EXCEEDED = "inactivity_duration_exceeded"
    TERMINATED_DUE_TO_UTILIZATION_POLICY = "terminated_due_to_utilization_policy"
    # set by the runner
    CONTAINER_EXITED_WITH_ERROR = "container_exited_with_error"
    PORTS_BINDING_FAILED = "ports_binding_failed"
    CREATING_CONTAINER_ERROR = "creating_container_error"
    EXECUTOR_ERROR = "executor_error"
    MAX_DURATION_EXCEEDED = "max_duration_exceeded"

    def to_status(self) -> JobStatus:
        failed = {
            self.FAILED_TO_START_DUE_TO_NO_CAPACITY,
            self.INTERRUPTED_BY_NO_CAPACITY,
            self.INSTANCE_UNREACHABLE,
            self.WAITING_INSTANCE_LIMIT_EXCEEDED,
            self.VOLUME_ERROR,
            self.GATEWAY_ERROR,
            self.CONTAINER_EXITED_WITH_ERROR,
            self.PORTS_BINDING_FAILED,
            self.CREATING_CONTAINER_ERROR,
            self.EXECUTOR_ERROR,
        }
        terminated = {
            self.TERMINATED_BY_USER,
            self.SCALED_DOWN,
            self.TERMINATED_BY_SERVER,
            self.INACTIVITY_DURATION_EXCEEDED,
            self.TERMINATED_DUE_TO_UTILIZATION_POLICY,
            self.MAX_DURATION_EXCEEDED,
        }
        if self in failed:
            return JobStatus.FAILED
        if self in terminated:
            return JobStatus.TERMINATED
        if self == self.ABORTED_BY_USER:
            return JobStatus.ABORTED
        return JobStatus.DONE


class RunStatus(str, Enum):
    PENDING = "pending"
    SUBMITTED = "submitted"
    PROVISIONING = "provisioning"
    RUNNING = "running"
    TERMINATING = "terminating"
    TERMINATED = "terminated"
    FAILED = "failed"
    DONE = "done"

    @classmethod
    def finished_statuses(cls) -> List["RunStatus"]:
        return [cls.TERMINATED, cls.FAILED, cls.DONE]

    def is_finished(self) -> bool:
        return self in self.finished_statuses()


class RunTerminationReason(str, Enum):
    ALL_JOBS_DONE = "all_jobs_done"
    JOB_FAILED = "job_failed"
    RETRY_LIMIT_EXCEEDED = "retry_limit_exceeded"
    STOPPED_BY_USER = "stopped_by_user"
    ABORTED_BY_USER = "aborted_by_user"
    INACTIVITY_DURATION_EXCEEDED = "inactivity_duration_exceeded"
    TERMINATED_DUE_TO_UTILIZATION_POLICY = "terminated_due_to_utilization_policy"
    SERVER_ERROR = "server_error"

    def to_status(self) -> RunStatus:
        if self == self.ALL_JOBS_DONE:
            return RunStatus.DONE
        if self in (
            self.STOPPED_BY_USER,
            self.ABORTED_BY_USER,
            self.INACTIVITY_DURATION_EXCEEDED,
            self.TERMINATED_DUE_TO_UTILIZATION_POLICY,
        ):
            return RunStatus.TERMINATED
        return RunStatus.FAILED

    def to_job_termination_reason(self) -> JobTerminationReason:
        if self == self.ALL_JOBS_DONE:
            return JobTerminationReason.DONE_BY_RUNNER
        if self == self.STOPPED_BY_USER:
            return JobTerminationReason.TERMINATED_BY_USER
        if self == self.ABORTED_BY_USER:
            return JobTerminationReason.ABORTED_BY_USER
        if self == self.INACTIVITY_DURATION_EXCEEDED:
            return JobTerminationReason.INACTIVITY_DURATION_EXCEEDED
        if self == self.TERMINATED_DUE_TO_UTILIZATION_POLICY:
            return JobTerminationReason.TERMINATED_DUE_TO_UTILIZATION_POLICY
        return JobTerminationReason.TERMINATED_BY_SERVER


class Requirements(CoreModel):
    resources: ResourcesSpec
    max_price: Optional[float] = None
    spot: Optional[bool] = None
    reservation: Optional[str] = None


class RunSpec(CoreModel):
    run_name: Optional[str] = None
    repo_id: Optional[str] = None
    repo_data: Optional[dict] = None
    configuration_path: Optional[str] = None
    configuration: AnyRunConfiguration
    profile: Profile = Field(default_factory=Profile)
    ssh_key_pub: Optional[str] = None

    def merged_profile(self) -> Profile:
        from dstack_tpu.core.models.profiles import merge_profiles

        return merge_profiles(self.profile, self.configuration.inline_profile())


class VolumeMount(CoreModel):
    """A volume mount as the agent sees it: where to put it, and how the host
    exposes it (a block device on cloud workers — /dev/disk/by-id/google-<id> for
    GCP data disks — or a host directory on the local backend)."""

    name: str
    path: str
    device: Optional[str] = None
    host_dir: Optional[str] = None


class JobSpec(CoreModel):
    replica_num: int = 0
    job_num: int = 0
    job_name: str
    # Set by the server at submit time: the job row id, unique per submission.
    # The agent labels containers with it so restart recovery never re-attaches to
    # a previous (retried) submission's leftover container.
    job_submission_id: Optional[str] = None
    jobs_per_replica: int = 1
    commands: List[str] = Field(default_factory=list)
    env: Dict[str, str] = Field(default_factory=dict)
    image_name: str
    registry_auth: Optional[RegistryAuth] = None
    privileged: bool = False
    user: Optional[str] = None
    home_dir: Optional[str] = None
    working_dir: Optional[str] = None
    repo_dir: Optional[str] = None
    max_duration: Optional[int] = None
    stop_duration: Optional[int] = None
    utilization_policy: Optional[UtilizationPolicy] = None
    retry: Optional[RetryPolicy] = None
    requirements: Requirements
    app_ports: List[int] = Field(default_factory=list)
    service_port: Optional[int] = None
    # Volume mounts; device/host_dir are resolved by the server at submit time.
    volumes: List[VolumeMount] = Field(default_factory=list)
    # Host-directory bind mounts (instance_path:path).
    instance_mounts: List[Dict[str, str]] = Field(default_factory=list)


class JobProvisioningData(CoreModel):
    """Where a job landed: backend identity + connectivity for one slice worker."""

    backend: str
    instance_type: InstanceType
    instance_id: str
    hostname: Optional[str] = None
    internal_ip: Optional[str] = None
    region: str = ""
    availability_zone: Optional[str] = None
    price: float = 0.0
    username: str = "root"
    ssh_port: int = 22
    ssh_proxy: Optional[SSHConnectionParams] = None
    dockerized: bool = True
    backend_data: Optional[str] = None
    # TPU slice identity
    slice_id: Optional[str] = None
    slice_name: Optional[str] = None
    worker_num: int = 0
    hosts_per_slice: int = 1


class JobRuntimeData(CoreModel):
    """Mutable per-submission runtime state (parity: reference JobRuntimeData runs.py:243).

    Stored in jobs.job_runtime_data; carries how the server reaches the runner and how
    far logs/state have been pulled."""

    runner_port: Optional[int] = None
    runner_pid: Optional[int] = None
    pull_offset: int = 0
    started_at: Optional[datetime.datetime] = None  # first observed RUNNING transition
    ports_mapping: Dict[int, int] = Field(default_factory=dict)
    # Service replicas: last readiness-probe outcome (TCP connect to the app
    # socket, process_services); the proxy prefers ready replicas.
    probe_ready: Optional[bool] = None
    volume_names: List[str] = Field(default_factory=list)


class ClusterInfo(CoreModel):
    """The TPU cluster contract injected into every job's environment.

    Replaces the reference's MPI hostfile + NCCL bootstrap (executor.go:262-274,707):
    JAX coordinator + per-worker identity + MegaScale DCN variables for multislice.
    """

    master_node_ip: str = ""
    node_ips: List[str] = Field(default_factory=list)
    nodes_num: int = 1
    node_rank: int = 0
    # Slice-local contract
    tpu_worker_id: int = 0
    tpu_worker_hostnames: List[str] = Field(default_factory=list)
    tpu_topology: Optional[str] = None
    tpu_generation: Optional[str] = None
    chips_per_host: int = 0
    # Cross-slice (multislice) contract
    num_slices: int = 1
    slice_id: int = 0
    coordinator_address: Optional[str] = None  # jax.distributed.initialize
    megascale_coordinator_address: Optional[str] = None

    def to_env(self) -> Dict[str, str]:
        env = {
            "DSTACK_NODE_RANK": str(self.node_rank),
            "DSTACK_NODES_NUM": str(self.nodes_num),
            "DSTACK_MASTER_NODE_IP": self.master_node_ip,
            "DSTACK_NODES_IPS": "\n".join(self.node_ips),
            "TPU_WORKER_ID": str(self.tpu_worker_id),
            "TPU_WORKER_HOSTNAMES": ",".join(self.tpu_worker_hostnames),
        }
        if self.chips_per_host:
            env["DSTACK_TPU_CHIPS_PER_HOST"] = str(self.chips_per_host)
        if self.tpu_topology:
            env["TPU_TOPOLOGY"] = self.tpu_topology
        if self.tpu_generation:
            env["DSTACK_TPU_GENERATION"] = self.tpu_generation
        if self.coordinator_address:
            env["DSTACK_JAX_COORDINATOR"] = self.coordinator_address
        if self.num_slices > 1:
            env["MEGASCALE_NUM_SLICES"] = str(self.num_slices)
            env["MEGASCALE_SLICE_ID"] = str(self.slice_id)
            if self.megascale_coordinator_address:
                env["MEGASCALE_COORDINATOR_ADDRESS"] = self.megascale_coordinator_address
        return env


class JobSubmission(CoreModel):
    id: uuid.UUID
    submission_num: int = 0
    submitted_at: datetime.datetime
    last_processed_at: Optional[datetime.datetime] = None
    finished_at: Optional[datetime.datetime] = None
    status: JobStatus
    termination_reason: Optional[JobTerminationReason] = None
    termination_reason_message: Optional[str] = None
    exit_status: Optional[int] = None
    job_provisioning_data: Optional[JobProvisioningData] = None
    inactivity_secs: Optional[int] = None

    @property
    def age(self) -> datetime.timedelta:
        return datetime.datetime.now(datetime.timezone.utc) - self.submitted_at


class Job(CoreModel):
    job_spec: JobSpec
    job_submissions: List[JobSubmission] = Field(default_factory=list)

    @property
    def latest(self) -> Optional[JobSubmission]:
        return self.job_submissions[-1] if self.job_submissions else None


class Run(CoreModel):
    id: uuid.UUID
    project_name: str
    user: str
    submitted_at: datetime.datetime
    last_processed_at: Optional[datetime.datetime] = None
    status: RunStatus
    status_message: Optional[str] = None
    termination_reason: Optional[RunTerminationReason] = None
    run_spec: RunSpec
    jobs: List[Job] = Field(default_factory=list)
    cost: float = 0.0
    service: Optional[ServiceSpec] = None
    error: Optional[str] = None
    # Which server replica's scheduler currently owns this run (run_leases);
    # None for finished runs and single-replica deployments without a lease.
    owner: Optional[str] = None

    @property
    def run_name(self) -> str:
        return self.run_spec.run_name or ""


class RunPlan(CoreModel):
    project_name: str
    user: str
    run_spec: RunSpec
    effective_run_name: Optional[str] = None
    job_plans: List[JobSpec] = Field(default_factory=list)
    offers: List[dict] = Field(default_factory=list)
    total_offers: int = 0
    max_offer_price: Optional[float] = None
    current_resource: Optional[Run] = None
    action: str = "create"
    # Plan-time registry introspection result (user/entrypoint/platform, or
    # verified=False when the registry was unreachable from the server).
    image_config: Optional[dict] = None


class ApplyRunPlanInput(CoreModel):
    run_spec: RunSpec
    force: bool = False
