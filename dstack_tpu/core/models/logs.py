"""Log event wire models (parity: reference core/models/logs.py)."""

from __future__ import annotations

import datetime
from enum import Enum
from typing import List

from pydantic import Field

from dstack_tpu.core.models.common import CoreModel


class LogEventSource(str, Enum):
    STDOUT = "stdout"
    STDERR = "stderr"


class LogEvent(CoreModel):
    timestamp: datetime.datetime
    log_source: LogEventSource = LogEventSource.STDOUT
    message: str  # base64-encoded bytes on the wire


class JobSubmissionLogs(CoreModel):
    logs: List[LogEvent] = Field(default_factory=list)
    next_token: str = ""
