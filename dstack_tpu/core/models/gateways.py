"""Gateway wire models (parity: reference core/models/gateways.py)."""

from __future__ import annotations

import datetime
import uuid
from enum import Enum
from typing import Optional

from dstack_tpu.core.models.common import CoreModel
from dstack_tpu.core.models.configurations import GatewayConfiguration


class GatewayStatus(str, Enum):
    SUBMITTED = "submitted"
    PROVISIONING = "provisioning"
    RUNNING = "running"
    FAILED = "failed"


class GatewayProvisioningData(CoreModel):
    instance_id: str
    ip_address: Optional[str] = None
    region: str = ""
    availability_zone: Optional[str] = None
    hostname: Optional[str] = None
    backend_data: Optional[str] = None


class Gateway(CoreModel):
    id: uuid.UUID
    name: str
    project_name: str
    configuration: GatewayConfiguration
    created_at: datetime.datetime
    status: GatewayStatus
    status_message: Optional[str] = None
    ip_address: Optional[str] = None
    hostname: Optional[str] = None
    default: bool = False
