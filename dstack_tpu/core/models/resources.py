"""Resource requirements DSL where the accelerator atom is a *TPU pod-slice topology*.

Parity: /root/reference src/dstack/_internal/core/models/resources.py (GPUSpec DSL,
`gpu: v5litepod-8` shorthand) — re-designed so TPU slices (generation × topology ×
slice count) are first-class rather than a vendor branch of a GPU spec.

Naming semantics (public TPU naming):
- v4 / v5p slice names count **TensorCores** (v5p-16 = 8 chips, 2 hosts of 4 chips).
- v5e (v5litepod) / v6e names count **chips** (v5litepod-8 = 8 chips, 1 host).
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional

from pydantic import Field, model_validator

from dstack_tpu.core.models.common import ConfigModel, CoreModel, MemoryRange, Range


class TpuGeneration(CoreModel):
    """Static description of one TPU generation."""

    name: str
    chips_per_host: int
    hbm_gb_per_chip: float
    bf16_tflops_per_chip: float
    # True when the slice name counts TensorCores (2 per chip) rather than chips.
    name_counts_cores: bool
    # Sorted valid chip counts for slices (sub-host sizes first where supported).
    valid_chip_counts: List[int]
    default_runtime_version: str


# Peak numbers are the public per-chip specs; used for offer metadata and MFU math.
TPU_GENERATIONS: Dict[str, TpuGeneration] = {
    g.name: g
    for g in [
        TpuGeneration(
            name="v4",
            chips_per_host=4,
            hbm_gb_per_chip=32,
            bf16_tflops_per_chip=275,
            name_counts_cores=True,
            valid_chip_counts=[4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048],
            default_runtime_version="tpu-ubuntu2204-base",
        ),
        TpuGeneration(
            name="v5e",
            chips_per_host=8,
            hbm_gb_per_chip=16,
            bf16_tflops_per_chip=197,
            name_counts_cores=False,
            valid_chip_counts=[1, 2, 4, 8, 16, 32, 64, 128, 256],
            default_runtime_version="v2-alpha-tpuv5-lite",
        ),
        TpuGeneration(
            name="v5p",
            chips_per_host=4,
            hbm_gb_per_chip=95,
            bf16_tflops_per_chip=459,
            name_counts_cores=True,
            valid_chip_counts=[4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 3072],
            default_runtime_version="v2-alpha-tpuv5",
        ),
        TpuGeneration(
            name="v6e",
            chips_per_host=4,
            hbm_gb_per_chip=32,
            bf16_tflops_per_chip=918,
            name_counts_cores=False,
            valid_chip_counts=[1, 4, 8, 16, 32, 64, 128, 256],
            default_runtime_version="v2-alpha-tpuv6e",
        ),
    ]
}

_GEN_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "v5": "v5p",
    "trillium": "v6e",
}

_SLICE_NAME_RE = re.compile(r"^(v\d+[a-z]*|v5litepod|trillium)-(\d+)$", re.IGNORECASE)


def normalize_generation(name: str) -> str:
    n = name.lower()
    n = _GEN_ALIASES.get(n, n)
    if n not in TPU_GENERATIONS:
        raise ValueError(
            f"unknown TPU generation {name!r}; known: {sorted(TPU_GENERATIONS)} "
            f"(aliases: {sorted(_GEN_ALIASES)})"
        )
    return n


class TpuSliceSpec(ConfigModel):
    """A concrete TPU pod slice: generation + chip count (+ derived topology/hosts).

    Accepted YAML forms::

        tpu: v5p-16                      # slice name
        tpu: {generation: v5e, chips: 8}
        tpu: {name: v5litepod-16}
        tpu: {generation: v5p, chips: 8, count: 2}   # 2 slices (multislice)
    """

    generation: str
    chips: int
    count: Range[int] = Field(default_factory=lambda: Range[int](min=1, max=1), description="Number of slices (multislice when >1)")

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if isinstance(v, str):
            return cls._parse_name(v)
        if isinstance(v, dict):
            v = dict(v)
            name = v.pop("name", None)
            if name is not None:
                if "generation" in v or "chips" in v:
                    raise ValueError("`name` cannot be combined with `generation`/`chips`")
                parsed = cls._parse_name(name)
                parsed.update(v)
                return parsed
            if "generation" in v:
                v["generation"] = normalize_generation(str(v["generation"]))
            return v
        return v

    @staticmethod
    def _parse_name(name: str) -> dict:
        m = _SLICE_NAME_RE.match(name.strip())
        if m is None:
            raise ValueError(f"invalid TPU slice name {name!r} (expected e.g. v5p-16, v5e-8, v6e-256)")
        gen = normalize_generation(m.group(1))
        n = int(m.group(2))
        chips = n // 2 if TPU_GENERATIONS[gen].name_counts_cores else n
        if chips < 1:
            raise ValueError(f"invalid TPU slice name {name!r}: too small")
        return {"generation": gen, "chips": chips}

    @model_validator(mode="after")
    def _validate(self):
        gen = TPU_GENERATIONS[self.generation]
        if self.chips not in gen.valid_chip_counts:
            raise ValueError(
                f"{self.generation} slices support chip counts {gen.valid_chip_counts}, got {self.chips}"
            )
        return self

    @property
    def gen_info(self) -> TpuGeneration:
        return TPU_GENERATIONS[self.generation]

    @property
    def hosts(self) -> int:
        return max(1, math.ceil(self.chips / self.gen_info.chips_per_host))

    @property
    def slice_name(self) -> str:
        n = self.chips * 2 if self.gen_info.name_counts_cores else self.chips
        return f"{self.generation}-{n}"

    @property
    def accelerator_type(self) -> str:
        """GCP TPU API accelerator type string."""
        if self.generation == "v5e":
            return f"v5litepod-{self.chips}"
        return self.slice_name

    @property
    def total_hbm_gb(self) -> float:
        return self.chips * self.gen_info.hbm_gb_per_chip

    @property
    def bf16_tflops(self) -> float:
        return self.chips * self.gen_info.bf16_tflops_per_chip

    def pretty(self) -> str:
        c = self.count
        prefix = "" if c.min == 1 and c.max == 1 else f"{c.pretty()}x "
        return f"{prefix}{self.slice_name} ({self.chips} chips, {self.hosts} hosts)"


def default_topology(generation: str, chips: int) -> str:
    """A reasonable ICI topology string for a chip count (e.g. 16 chips v5p -> 2x2x4)."""
    gen = TPU_GENERATIONS[normalize_generation(generation)]
    if gen.name in ("v5e", "v6e"):  # 2-D tori
        if chips == 1:
            return "1x1"
        a = 2 ** (int(math.log2(chips)) // 2)
        return f"{a}x{chips // a}"
    # 3-D tori (v4/v5p); factor so non-power-of-two counts (e.g. 3072 = 3*1024) work
    dims = [1, 1, 1]
    i = 0
    remaining = chips
    while remaining > 1:
        factor = next((p for p in (2, 3, 5, 7) if remaining % p == 0), remaining)
        dims[i % 3] *= factor
        remaining //= factor
        i += 1
    dims.sort()
    return "x".join(str(d) for d in dims)


class CpuSpec(ConfigModel):
    """CPU requirement: count range + optional arch (parity: resources.py CPUSpec)."""

    arch: Optional[str] = None
    count: Range[int] = Field(default_factory=lambda: Range[int](min=2))

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if v is None:
            return v
        if isinstance(v, (int, str)) and not isinstance(v, bool):
            s = str(v)
            if ":" in s:
                arch, _, cnt = s.partition(":")
                return {"arch": arch or None, "count": cnt}
            return {"count": s}
        return v


class DiskSpec(ConfigModel):
    size: MemoryRange = Field(default_factory=lambda: MemoryRange(min=100.0))

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if isinstance(v, (int, float, str)) and not isinstance(v, bool):
            return {"size": v}
        return v


class ResourcesSpec(ConfigModel):
    """The `resources:` block of a run configuration.

    TPU-first: `tpu:` names a pod slice; `gpu:`-style specs from the reference are out of
    scope (the framework targets TPU fleets; CPU-only runs use cpu/memory/disk alone).
    """

    tpu: Optional[TpuSliceSpec] = None
    cpu: CpuSpec = Field(default_factory=CpuSpec)
    memory: MemoryRange = Field(default_factory=lambda: MemoryRange(min=8.0))
    shm_size: Optional[MemoryRange] = None
    disk: Optional[DiskSpec] = Field(default_factory=DiskSpec)

    def pretty(self) -> str:
        parts = [f"cpu={self.cpu.count.pretty()}", f"mem={self.memory.pretty()}GB"]
        if self.tpu is not None:
            parts.insert(0, f"tpu={self.tpu.pretty()}")
        if self.disk is not None:
            parts.append(f"disk={self.disk.size.pretty()}GB")
        return ", ".join(parts)
