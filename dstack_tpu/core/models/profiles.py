"""Run profiles: scheduling/lifecycle knobs shared by all configuration types.

Parity: /root/reference src/dstack/_internal/core/models/profiles.py (SpotPolicy,
RetryEvent, utilization policy, startup_order/stop_criteria, idle duration).
"""

from __future__ import annotations

from enum import Enum
from typing import Annotated, List, Optional, Union

from pydantic import BeforeValidator, Field, model_validator

from dstack_tpu.core.models.common import ConfigModel, Duration, parse_duration

DEFAULT_RUN_TERMINATION_IDLE_TIME = 5 * 60
DEFAULT_FLEET_TERMINATION_IDLE_TIME = 3 * 24 * 3600


class SpotPolicy(str, Enum):
    SPOT = "spot"
    ONDEMAND = "on-demand"
    AUTO = "auto"


class CreationPolicy(str, Enum):
    REUSE = "reuse"
    REUSE_OR_CREATE = "reuse-or-create"


class TerminationPolicy(str, Enum):
    DONT_DESTROY = "dont-destroy"
    DESTROY_AFTER_IDLE = "destroy-after-idle"


class RetryEvent(str, Enum):
    NO_CAPACITY = "no-capacity"
    INTERRUPTION = "interruption"
    ERROR = "error"


class StartupOrder(str, Enum):
    ANY = "any"
    MASTER_FIRST = "master-first"
    WORKERS_FIRST = "workers-first"


class StopCriteria(str, Enum):
    ALL_DONE = "all-done"
    MASTER_DONE = "master-done"


class UtilizationPolicy(ConfigModel):
    """Terminate a run whose accelerator duty-cycle stays below a threshold for a window."""

    min_tpu_utilization: int = Field(ge=0, le=100, description="Percent duty cycle")
    time_window: Duration = Field(description="Window over which utilization is evaluated")

    @model_validator(mode="after")
    def _check(self):
        if self.time_window is None or self.time_window < 60:
            raise ValueError("time_window must be at least 1m")
        return self


class RetryPolicy(ConfigModel):
    """`retry: true` | duration | {on_events: [...], duration: 1h}."""

    on_events: List[RetryEvent] = Field(
        default_factory=lambda: [RetryEvent.NO_CAPACITY, RetryEvent.INTERRUPTION, RetryEvent.ERROR]
    )
    duration: Duration = 3600

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if v is True:
            return {}
        if isinstance(v, (int, str)) and not isinstance(v, bool):
            return {"duration": v}
        return v


def parse_retry(v):
    """Field-site parser so `retry: false` disables retry instead of failing validation."""
    if v is False or v is None:
        return None
    return v


RetryField = Annotated[Optional[RetryPolicy], BeforeValidator(parse_retry)]


class Profile(ConfigModel):
    """Named profile; all fields overlay onto run configurations."""

    name: Optional[str] = None
    backends: Optional[List[str]] = None
    regions: Optional[List[str]] = None
    availability_zones: Optional[List[str]] = None
    instance_types: Optional[List[str]] = None
    reservation: Optional[str] = None
    spot_policy: Optional[SpotPolicy] = None
    retry: RetryField = None
    max_duration: Optional[Union[int, str]] = None
    stop_duration: Optional[Union[int, str]] = None
    max_price: Optional[float] = Field(default=None, gt=0)
    creation_policy: Optional[CreationPolicy] = None
    idle_duration: Optional[Union[int, str]] = None
    utilization_policy: Optional[UtilizationPolicy] = None
    startup_order: Optional[StartupOrder] = None
    stop_criteria: Optional[StopCriteria] = None
    fleets: Optional[List[str]] = None
    tags: Optional[dict] = None

    def normalized_max_duration(self) -> Optional[int]:
        return parse_duration(self.max_duration)

    def normalized_idle_duration(self) -> Optional[int]:
        return parse_duration(self.idle_duration)


def merge_profiles(base: Profile, overlay: Profile) -> Profile:
    """Overlay explicitly-set fields of `overlay` onto `base` (overlay wins).

    Uses fields-set rather than non-None so an explicit `off` (-> None) in the overlay
    disables a policy from the base instead of being silently dropped.
    """
    data = base.model_dump(exclude_unset=True)
    data.update(overlay.model_dump(exclude_unset=True))
    return Profile.model_validate(data)
