"""Metrics wire models (parity: reference core/models/metrics.py). GPU util is replaced
by TPU duty-cycle / tensorcore utilization and per-chip HBM usage."""

from __future__ import annotations

import datetime
from typing import List, Optional

from pydantic import Field

from dstack_tpu.core.models.common import CoreModel


class MetricPoint(CoreModel):
    timestamp: datetime.datetime
    cpu_usage_percent: float = 0.0
    memory_usage_bytes: int = 0
    memory_working_set_bytes: int = 0
    tpu_duty_cycle_percent: Optional[float] = None
    tpu_hbm_usage_bytes: Optional[int] = None
    tpu_tensorcore_util_percent: Optional[float] = None


class JobMetrics(CoreModel):
    points: List[MetricPoint] = Field(default_factory=list)
