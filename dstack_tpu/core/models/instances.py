"""Instance & offer wire models.

Parity: /root/reference src/dstack/_internal/core/models/instances.py. TPU twist: an
*offer* is a whole pod slice; `hosts_per_slice > 1` means one cloud resource backs
multiple instance rows (worker ≠ instance — SURVEY §7 hard part (a))."""

from __future__ import annotations

import datetime
import uuid
from enum import Enum
from typing import List, Optional

from pydantic import Field

from dstack_tpu.core.models.common import CoreModel
from dstack_tpu.core.models.resources import TpuSliceSpec


class TpuResources(CoreModel):
    """Accelerator inventory of one offer (whole slice) or one instance (one host)."""

    generation: Optional[str] = None
    chips: int = 0
    hosts: int = 1
    topology: Optional[str] = None
    hbm_gb: float = 0.0
    bf16_tflops: float = 0.0

    @classmethod
    def from_slice(cls, s: TpuSliceSpec, topology: Optional[str] = None) -> "TpuResources":
        return cls(
            generation=s.generation,
            chips=s.chips,
            hosts=s.hosts,
            topology=topology,
            hbm_gb=s.total_hbm_gb,
            bf16_tflops=s.bf16_tflops,
        )


class HostResources(CoreModel):
    cpus: int = 0
    memory_gb: float = 0.0
    disk_gb: float = 100.0
    spot: bool = False
    tpu: Optional[TpuResources] = None

    def pretty(self) -> str:
        parts = [f"{self.cpus}xCPU", f"{self.memory_gb:g}GB"]
        if self.tpu is not None and self.tpu.chips:
            parts.append(f"tpu:{self.tpu.generation}:{self.tpu.chips}chips")
        if self.spot:
            parts.append("spot")
        return ", ".join(parts)


class InstanceType(CoreModel):
    name: str
    resources: HostResources


class InstanceAvailability(str, Enum):
    UNKNOWN = "unknown"
    AVAILABLE = "available"
    NOT_AVAILABLE = "not_available"
    NO_QUOTA = "no_quota"
    IDLE = "idle"
    BUSY = "busy"

    def is_available(self) -> bool:
        return self in (self.UNKNOWN, self.AVAILABLE, self.IDLE)


class InstanceOffer(CoreModel):
    backend: str
    instance: InstanceType
    region: str
    price: float  # $/hr for the whole slice
    availability: InstanceAvailability = InstanceAvailability.UNKNOWN
    availability_zones: Optional[List[str]] = None
    # TPU specifics: one offer may be a multi-host slice — provisioned atomically.
    slice_name: Optional[str] = None  # e.g. v5p-16
    hosts_per_slice: int = 1
    spot: bool = False

    @property
    def total_hosts(self) -> int:
        return self.hosts_per_slice


class InstanceStatus(str, Enum):
    PENDING = "pending"
    PROVISIONING = "provisioning"
    IDLE = "idle"
    BUSY = "busy"
    TERMINATING = "terminating"
    TERMINATED = "terminated"

    def is_available(self) -> bool:
        return self in (self.IDLE, self.BUSY)

    @classmethod
    def finished_statuses(cls) -> List["InstanceStatus"]:
        return [cls.TERMINATING, cls.TERMINATED]

    def is_active(self) -> bool:
        return self not in self.finished_statuses()


class SSHConnectionParams(CoreModel):
    hostname: str
    username: str = "root"
    port: int = 22
    proxy_jump: Optional[str] = None


class RemoteConnectionInfo(CoreModel):
    host: str
    port: int = 22
    ssh_user: str = "root"
    ssh_proxy: Optional[SSHConnectionParams] = None


class Instance(CoreModel):
    id: uuid.UUID
    project_name: str
    backend: Optional[str] = None
    instance_type: Optional[InstanceType] = None
    name: str
    fleet_id: Optional[uuid.UUID] = None
    fleet_name: Optional[str] = None
    instance_num: int = 0
    hostname: Optional[str] = None
    status: InstanceStatus
    unreachable: bool = False
    termination_reason: Optional[str] = None
    created: datetime.datetime
    region: Optional[str] = None
    availability_zone: Optional[str] = None
    price: Optional[float] = None
    # TPU slice identity: all hosts of one slice share slice_id; worker_num orders them.
    slice_id: Optional[str] = None
    slice_name: Optional[str] = None
    worker_num: int = 0
    hosts_per_slice: int = 1
    total_blocks: int = 1
    busy_blocks: int = 0
