"""Fleet wire models (parity: reference core/models/fleets.py)."""

from __future__ import annotations

import datetime
import uuid
from enum import Enum
from typing import List, Optional

from pydantic import Field

from dstack_tpu.core.models.common import CoreModel
from dstack_tpu.core.models.configurations import FleetConfiguration
from dstack_tpu.core.models.instances import Instance


class FleetStatus(str, Enum):
    SUBMITTED = "submitted"
    ACTIVE = "active"
    TERMINATING = "terminating"
    TERMINATED = "terminated"
    FAILED = "failed"


class FleetSpec(CoreModel):
    configuration: FleetConfiguration
    configuration_path: Optional[str] = None


class Fleet(CoreModel):
    id: uuid.UUID
    name: str
    project_name: str
    spec: FleetSpec
    created_at: datetime.datetime
    status: FleetStatus
    status_message: Optional[str] = None
    instances: List[Instance] = Field(default_factory=list)


class FleetPlan(CoreModel):
    project_name: str
    user: str
    spec: FleetSpec
    effective_name: Optional[str] = None
    current_resource: Optional[Fleet] = None
    offers: List[dict] = Field(default_factory=list)
    total_offers: int = 0
    max_offer_price: Optional[float] = None
    action: str = "create"


class ApplyFleetPlanInput(CoreModel):
    spec: FleetSpec
    force: bool = False
