"""Shared model base classes and scalar DSL types.

Parity: /root/reference src/dstack/_internal/core/models/common.py and the
Memory/Duration/Range DSL in .../models/resources.py:1-120 — re-designed for pydantic v2
(annotated validators instead of v1 custom types).
"""

from __future__ import annotations

import re
from enum import Enum
from typing import Annotated, Generic, Optional, TypeVar, Union

from pydantic import (
    BaseModel,
    BeforeValidator,
    ConfigDict,
    PlainSerializer,
    model_validator,
)


class CoreModel(BaseModel):
    """Wire models: tolerant of unknown fields for forward compatibility."""

    model_config = ConfigDict(populate_by_name=True, extra="ignore")


class ConfigModel(BaseModel):
    """User-authored YAML configuration models: unknown keys are an error."""

    model_config = ConfigDict(populate_by_name=True, extra="forbid")


class RegistryAuth(CoreModel):
    username: Optional[str] = None
    password: Optional[str] = None


_DURATION_RE = re.compile(r"^\s*(\d+)\s*(s|m|h|d|w)?\s*$")
_DURATION_UNITS = {"s": 1, "m": 60, "h": 3600, "d": 86400, "w": 604800, None: 1}


def parse_duration(v: Union[int, str, None]) -> Optional[int]:
    """'90s' | '15m' | '2h' | '1d' | 'off' | int seconds -> seconds (or None for 'off')."""
    if v is None:
        return None
    if isinstance(v, bool):
        raise ValueError("invalid duration")
    if isinstance(v, (int, float)):
        return int(v)
    s = v.strip().lower()
    if s in ("off", "-1"):
        return None
    m = _DURATION_RE.match(s)
    if m is None:
        raise ValueError(f"invalid duration: {v!r} (expected e.g. 30s, 15m, 2h, 1d)")
    return int(m.group(1)) * _DURATION_UNITS[m.group(2)]


def format_duration(seconds: Optional[int]) -> str:
    if seconds is None:
        return "off"
    for unit, div in (("w", 604800), ("d", 86400), ("h", 3600), ("m", 60)):
        if seconds and seconds % div == 0:
            return f"{seconds // div}{unit}"
    return f"{seconds}s"


Duration = Annotated[
    Optional[int],
    BeforeValidator(parse_duration),
    PlainSerializer(lambda v: v, return_type=Optional[int]),
]


_MEMORY_RE = re.compile(r"^\s*(\d+(?:\.\d+)?)\s*(tb|gb|mb|kb|b)?\s*$", re.IGNORECASE)
_MEMORY_UNITS = {"tb": 1024.0, "gb": 1.0, "mb": 1 / 1024, "kb": 1 / 1024**2, "b": 1 / 1024**3, None: 1.0}


def parse_memory(v: Union[int, float, str]) -> float:
    """'16GB' | '512MB' | 16 -> gibibytes (float)."""
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    m = _MEMORY_RE.match(str(v))
    if m is None:
        raise ValueError(f"invalid memory size: {v!r} (expected e.g. 512MB, 16GB, 1TB)")
    unit = m.group(2).lower() if m.group(2) else None
    return float(m.group(1)) * _MEMORY_UNITS[unit]


def format_memory(gb: float) -> str:
    if gb >= 1024 and gb % 1024 == 0:
        return f"{int(gb // 1024)}TB"
    if gb == int(gb):
        return f"{int(gb)}GB"
    return f"{int(gb * 1024)}MB"


Memory = Annotated[float, BeforeValidator(parse_memory)]

T = TypeVar("T", int, float)


class Range(BaseModel, Generic[T]):
    """Inclusive numeric range DSL: 4 | '4..8' | '4..' | '..8' | {min: 4, max: 8}."""

    model_config = ConfigDict(extra="forbid")

    min: Optional[T] = None
    max: Optional[T] = None

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if v is None or isinstance(v, dict):
            return v
        if isinstance(v, Range):
            return {"min": v.min, "max": v.max}
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return {"min": v, "max": v}
        if isinstance(v, str):
            s = v.replace(" ", "")
            if ".." in s:
                lo, _, hi = s.partition("..")
                return {"min": lo or None, "max": hi or None}
            return {"min": s, "max": s}
        raise ValueError(f"invalid range: {v!r}")

    @model_validator(mode="after")
    def _check(self):
        if self.min is None and self.max is None:
            raise ValueError("range must have at least one bound")
        if self.min is not None and self.max is not None and self.min > self.max:
            raise ValueError(f"range min>{'max'}: {self.min}..{self.max}")
        return self

    def contains(self, value: Union[int, float]) -> bool:
        if self.min is not None and value < self.min:
            return False
        if self.max is not None and value > self.max:
            return False
        return True

    def intersects(self, other: "Range") -> bool:
        lo = max(x for x in (self.min, other.min) if x is not None) if (self.min is not None or other.min is not None) else None
        hi = min(x for x in (self.max, other.max) if x is not None) if (self.max is not None or other.max is not None) else None
        if lo is None or hi is None:
            return True
        return lo <= hi

    def pretty(self) -> str:
        if self.min == self.max:
            return str(self.min)
        lo = "" if self.min is None else str(self.min)
        hi = "" if self.max is None else str(self.max)
        return f"{lo}..{hi}"


class MemoryRange(Range[float]):
    @model_validator(mode="before")
    @classmethod
    def _parse_mem(cls, v):
        if isinstance(v, str) and ".." in v:
            s = v.replace(" ", "")
            lo, _, hi = s.partition("..")
            return {"min": parse_memory(lo) if lo else None, "max": parse_memory(hi) if hi else None}
        if isinstance(v, (str, int, float)) and not isinstance(v, bool):
            g = parse_memory(v)
            return {"min": g, "max": g}
        if isinstance(v, dict):
            return {
                "min": parse_memory(v["min"]) if v.get("min") is not None else None,
                "max": parse_memory(v["max"]) if v.get("max") is not None else None,
            }
        return v


class ApplyAction(str, Enum):
    CREATE = "create"
    UPDATE = "update"
