"""Volume wire models (parity: reference core/models/volumes.py). TPU data disks attach
to every host of a slice (reference gcp/compute.py:1003-1016)."""

from __future__ import annotations

import datetime
import uuid
from enum import Enum
from typing import List, Optional

from pydantic import Field

from dstack_tpu.core.models.common import CoreModel
from dstack_tpu.core.models.configurations import VolumeConfiguration


class VolumeStatus(str, Enum):
    SUBMITTED = "submitted"
    PROVISIONING = "provisioning"
    ACTIVE = "active"
    FAILED = "failed"

    def is_active(self) -> bool:
        return self != VolumeStatus.FAILED


class VolumeProvisioningData(CoreModel):
    backend: Optional[str] = None
    volume_id: str
    size_gb: float = 0
    availability_zone: Optional[str] = None
    price: Optional[float] = None
    attachable: bool = True
    detachable: bool = True
    backend_data: Optional[str] = None


class VolumeAttachment(CoreModel):
    instance_id: uuid.UUID
    instance_name: Optional[str] = None
    device_name: Optional[str] = None


class Volume(CoreModel):
    id: uuid.UUID
    name: str
    project_name: str
    user: Optional[str] = None
    configuration: VolumeConfiguration
    external: bool = False
    created_at: datetime.datetime
    last_job_processed_at: Optional[datetime.datetime] = None
    status: VolumeStatus
    status_message: Optional[str] = None
    deleted: bool = False
    volume_id: Optional[str] = None
    provisioning_data: Optional[VolumeProvisioningData] = None
    attachments: List[VolumeAttachment] = Field(default_factory=list)
