"""Declarative run/resource configurations (the YAML a user `apply`s).

Parity: /root/reference src/dstack/_internal/core/models/configurations.py
(TaskConfiguration:355, ServiceConfiguration:479, DevEnvironmentConfiguration:345,
discriminated union :495-545) and fleets.py/volumes.py/gateways.py configuration models —
re-designed TPU-first: no GPU/CUDA knobs, `resources.tpu` is a slice topology, and
multi-node tasks map onto slice hosts (`nodes` = hosts of a slice, auto-derived).
"""

from __future__ import annotations

from enum import Enum
from typing import Annotated, Any, Dict, List, Literal, Optional, Union

from pydantic import Field, TypeAdapter, model_validator

from dstack_tpu.core.errors import ConfigurationError
from dstack_tpu.core.models.common import ConfigModel, Duration, RegistryAuth
from dstack_tpu.core.models.envs import Env
from dstack_tpu.core.models.profiles import (
    Profile,
    RetryField,
    StartupOrder,
    StopCriteria,
    UtilizationPolicy,
)
from dstack_tpu.core.models.resources import ResourcesSpec
from dstack_tpu.core.models.services import ModelSpec, RateLimit, ScalingSpec

DEFAULT_REPO_DIR = "/workflow"
DEFAULT_TPU_IMAGE = "dstack-tpu/base:latest"  # docker/tpu image: libtpu + JAX/XLA + sshd
DEFAULT_IDE_PORT = 8010  # dev-environment IDE backend port (attach target)


class PortMapping(ConfigModel):
    local_port: Optional[int] = None
    container_port: int

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if isinstance(v, int):
            return {"container_port": v}
        if isinstance(v, str):
            if ":" in v:
                lo, _, co = v.partition(":")
                return {"local_port": int(lo) if lo != "*" else None, "container_port": int(co)}
            return {"container_port": int(v)}
        return v


class VolumeMountPoint(ConfigModel):
    name: str
    path: str

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if isinstance(v, str):
            name, _, path = v.partition(":")
            if not path:
                raise ValueError(f"volume mount must be 'name:/path', got {v!r}")
            return {"name": name, "path": path}
        return v


class InstanceMountPoint(ConfigModel):
    instance_path: str
    path: str
    optional: bool = False

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if isinstance(v, str):
            ip, _, path = v.partition(":")
            if not path:
                raise ValueError(f"instance mount must be '/host/path:/container/path', got {v!r}")
            return {"instance_path": ip, "path": path}
        return v


AnyMountPoint = Union[VolumeMountPoint, InstanceMountPoint]


def _parse_mount(v):
    if isinstance(v, str) and v.startswith("/"):
        return InstanceMountPoint.model_validate(v)
    if isinstance(v, dict) and ("instance_path" in v):
        return InstanceMountPoint.model_validate(v)
    if isinstance(v, (str, dict)):
        return VolumeMountPoint.model_validate(v)
    return v


class BaseRunConfiguration(ConfigModel):
    name: Optional[str] = Field(default=None, description="The run name; auto-generated if omitted")
    image: Optional[str] = Field(default=None, description="Container image (defaults to the TPU base image)")
    privileged: bool = False
    entrypoint: Optional[str] = None
    registry_auth: Optional[RegistryAuth] = None
    python: Optional[str] = Field(default=None, description="Python version for the default image")
    env: Env = Field(default_factory=Env)
    resources: ResourcesSpec = Field(default_factory=ResourcesSpec)
    volumes: List[Annotated[AnyMountPoint, "mount"]] = Field(default_factory=list)
    working_dir: Optional[str] = None
    home_dir: str = "/root"
    repo_dir: str = DEFAULT_REPO_DIR
    # Profile overlay fields, inline:
    backends: Optional[List[str]] = None
    regions: Optional[List[str]] = None
    availability_zones: Optional[List[str]] = None
    spot_policy: Optional[str] = None
    retry: RetryField = None
    max_duration: Duration = None
    stop_duration: Duration = None  # default applied by the job configurator (300s)
    max_price: Optional[float] = Field(default=None, gt=0)
    creation_policy: Optional[str] = None
    idle_duration: Duration = None
    utilization_policy: Optional[UtilizationPolicy] = None
    reservation: Optional[str] = None
    fleets: Optional[List[str]] = None
    tags: Optional[Dict[str, str]] = None

    _PROFILE_FIELDS = (
        "backends",
        "regions",
        "availability_zones",
        "spot_policy",
        "retry",
        "max_duration",
        "stop_duration",
        "max_price",
        "creation_policy",
        "idle_duration",
        "utilization_policy",
        "reservation",
        "fleets",
        "tags",
    )

    @model_validator(mode="before")
    @classmethod
    def _parse_volumes(cls, values):
        if isinstance(values, dict) and isinstance(values.get("volumes"), list):
            values = dict(values)
            values["volumes"] = [_parse_mount(v) for v in values["volumes"]]
        return values

    def inline_profile(self) -> Profile:
        """Only fields the user actually set in the configuration, so the profile merge
        can distinguish 'unset' from an explicit value (incl. an explicit `off`)."""
        fields = {
            name: getattr(self, name)
            for name in self._PROFILE_FIELDS
            if name in self.model_fields_set
        }
        return Profile(**fields)


class TaskConfiguration(BaseRunConfiguration):
    """A batch job; on a multi-host TPU slice one job runs per host (gang-scheduled)."""

    type: Literal["task"] = "task"
    commands: List[str] = Field(default_factory=list)
    nodes: int = Field(default=0, ge=0, description="Hosts; 0 = derive from the TPU slice topology")
    ports: List[PortMapping] = Field(default_factory=list)
    startup_order: StartupOrder = StartupOrder.ANY
    stop_criteria: StopCriteria = StopCriteria.ALL_DONE
    elastic: Optional[List[str]] = Field(
        default=None,
        description=(
            "Alternative TPU slice topologies (e.g. [v5e-8, v5e-4]) a gang"
            " retry may resubmit onto when the original slice is preempted or"
            " out of capacity — tried in order, wrapping. The workload must"
            " tolerate the topology change (checkpoint + --resume re-shards"
            " state on load)."
        ),
    )

    @model_validator(mode="after")
    def _check(self):
        if not self.commands and self.entrypoint is None and self.image is None:
            raise ValueError(
                "task requires `commands` (or `entrypoint`, or an `image` whose own"
                " entrypoint runs the job)"
            )
        if self.elastic:
            from dstack_tpu.core.models.resources import TpuSliceSpec

            if self.resources.tpu is None:
                raise ValueError("`elastic` requires a `resources.tpu` request")
            for topo in self.elastic:
                TpuSliceSpec.model_validate(topo)  # fail at submit, not at rescue
        return self


class ServiceConfiguration(BaseRunConfiguration):
    """A long-running inference service behind the proxy/gateway with autoscaling."""

    type: Literal["service"] = "service"
    commands: List[str] = Field(default_factory=list)
    port: PortMapping
    gateway: Optional[Union[bool, str]] = None
    strip_prefix: bool = True
    model: Optional[ModelSpec] = None
    https: bool = True
    auth: bool = True
    replicas: Any = 1
    scaling: Optional[ScalingSpec] = None
    rate_limits: List[RateLimit] = Field(default_factory=list)
    probes: List[Any] = Field(default_factory=list)

    @model_validator(mode="after")
    def _check(self):
        from dstack_tpu.core.models.common import Range

        self.replicas = Range[int].model_validate(self.replicas)
        if self.replicas.min is None:
            self.replicas.min = 0
        if self.replicas.max is None:
            self.replicas.max = self.replicas.min
        if self.replicas.min != self.replicas.max and self.scaling is None:
            raise ValueError("autoscaling range of replicas requires `scaling` to be set")
        if not self.commands and self.entrypoint is None and self.image is None:
            raise ValueError(
                "service requires `commands` (or `entrypoint`, or an `image` whose own"
                " entrypoint serves the port)"
            )
        return self


class IDE(str, Enum):
    VSCODE = "vscode"
    CURSOR = "cursor"


class DevEnvironmentConfiguration(BaseRunConfiguration):
    """An interactive TPU VM with an IDE bootstrap and a JAX-ready environment."""

    type: Literal["dev-environment"] = "dev-environment"
    ide: IDE = IDE.VSCODE
    version: Optional[str] = None
    init: List[str] = Field(default_factory=list)
    inactivity_duration: Duration = None


AnyRunConfiguration = Annotated[
    Union[TaskConfiguration, ServiceConfiguration, DevEnvironmentConfiguration],
    Field(discriminator="type"),
]


# ---------------------------------------------------------------------------------------
# Fleet / volume / gateway configurations


class SSHHostParams(ConfigModel):
    hostname: str
    port: int = 22
    user: Optional[str] = None
    identity_file: Optional[str] = None
    proxy_jump: Optional[str] = None

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if isinstance(v, str):
            return {"hostname": v}
        return v


class SSHParams(ConfigModel):
    user: Optional[str] = None
    identity_file: Optional[str] = None
    hosts: List[SSHHostParams] = Field(default_factory=list)
    network: Optional[str] = None
    proxy_jump: Optional[str] = None


class InstanceGroupPlacement(str, Enum):
    ANY = "any"
    CLUSTER = "cluster"


class FleetConfiguration(ConfigModel):
    """A fleet is a set of instances; a cloud TPU fleet's atom is a pod slice
    (`resources.tpu`), where one slice = `hosts` instances gang-provisioned together.
    """

    type: Literal["fleet"] = "fleet"
    name: Optional[str] = None
    env: Env = Field(default_factory=Env)
    ssh_config: Optional[SSHParams] = None
    nodes: Optional[Any] = None  # Range: instance count for cloud fleets
    placement: InstanceGroupPlacement = InstanceGroupPlacement.ANY
    resources: ResourcesSpec = Field(default_factory=ResourcesSpec)
    backends: Optional[List[str]] = None
    regions: Optional[List[str]] = None
    availability_zones: Optional[List[str]] = None
    instance_types: Optional[List[str]] = None
    spot_policy: Optional[str] = None
    max_price: Optional[float] = Field(default=None, gt=0)
    idle_duration: Duration = None
    reservation: Optional[str] = None
    tags: Optional[Dict[str, str]] = None

    @model_validator(mode="after")
    def _check(self):
        from dstack_tpu.core.models.common import Range

        if self.ssh_config is None and self.nodes is None:
            self.nodes = 1
        if self.nodes is not None:
            self.nodes = Range[int].model_validate(self.nodes)
        if self.ssh_config is not None and self.nodes is not None:
            raise ValueError("`nodes` and `ssh_config` are mutually exclusive")
        if self.ssh_config is not None and not self.ssh_config.hosts:
            raise ValueError("ssh_config requires at least one host")
        return self


class VolumeConfiguration(ConfigModel):
    type: Literal["volume"] = "volume"
    name: Optional[str] = None
    backend: str = "gcp"
    region: str
    availability_zone: Optional[str] = None
    size: Optional[Any] = None  # Memory, e.g. "100GB"
    volume_id: Optional[str] = Field(default=None, description="Register an existing disk instead of creating one")
    auto_cleanup_duration: Duration = None
    tags: Optional[Dict[str, str]] = None

    @model_validator(mode="after")
    def _check(self):
        from dstack_tpu.core.models.common import parse_memory

        if self.size is None and self.volume_id is None:
            raise ValueError("either `size` or `volume_id` must be set")
        if self.size is not None:
            self.size = parse_memory(self.size)
        return self


class GatewayConfiguration(ConfigModel):
    type: Literal["gateway"] = "gateway"
    name: Optional[str] = None
    backend: str = "gcp"
    region: str
    domain: Optional[str] = None
    public_ip: bool = True
    certificate: Optional[Dict[str, Any]] = None
    tags: Optional[Dict[str, str]] = None


AnyConfiguration = Annotated[
    Union[
        TaskConfiguration,
        ServiceConfiguration,
        DevEnvironmentConfiguration,
        FleetConfiguration,
        VolumeConfiguration,
        GatewayConfiguration,
    ],
    Field(discriminator="type"),
]

_any_configuration_adapter: TypeAdapter = TypeAdapter(AnyConfiguration)
_any_run_configuration_adapter: TypeAdapter = TypeAdapter(AnyRunConfiguration)


def parse_configuration(data: dict) -> AnyConfiguration:
    if not isinstance(data, dict) or "type" not in data:
        raise ConfigurationError("configuration must be a mapping with a `type` key")
    try:
        return _any_configuration_adapter.validate_python(data)
    except Exception as e:
        raise ConfigurationError(str(e)) from e


def parse_run_configuration(data: dict) -> Union[TaskConfiguration, ServiceConfiguration, DevEnvironmentConfiguration]:
    try:
        return _any_run_configuration_adapter.validate_python(data)
    except Exception as e:
        raise ConfigurationError(str(e)) from e
