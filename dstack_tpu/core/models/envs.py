"""Environment-variable DSL: `env:` as dict or list of NAME=VALUE / bare NAME entries.

Parity: /root/reference src/dstack/_internal/core/models/envs.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union

from pydantic import model_validator

from dstack_tpu.core.models.common import ConfigModel


class Env(ConfigModel):
    """Bare names (no '=') must be supplied from the caller's environment at submit."""

    values: Dict[str, Optional[str]] = {}

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if isinstance(v, Env):
            return {"values": dict(v.values)}
        if isinstance(v, dict) and "values" not in v:
            return {"values": {str(k): None if val is None else str(val) for k, val in v.items()}}
        if isinstance(v, list):
            out: Dict[str, Optional[str]] = {}
            for item in v:
                s = str(item)
                if "=" in s:
                    k, _, val = s.partition("=")
                    out[k] = val
                else:
                    out[s] = None
            return {"values": out}
        return v

    def as_dict(self) -> Dict[str, str]:
        missing = [k for k, v in self.values.items() if v is None]
        if missing:
            raise ValueError(f"env variables without values must be set at submit time: {missing}")
        return {k: v for k, v in self.values.items() if v is not None}

    def update(self, other: Union["Env", Dict[str, str]]) -> None:
        if isinstance(other, Env):
            self.values.update(other.values)
        else:
            self.values.update(other)
