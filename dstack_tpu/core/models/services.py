"""Service-specific config: autoscaling, model registry entry, rate limits.

Parity: /root/reference core/models/configurations.py ScalingSpec:71, RateLimit:112,
core/models/services.py (OpenAI-compatible model mapping).
"""

from __future__ import annotations

from enum import Enum
from typing import Optional

from pydantic import Field, model_validator

from dstack_tpu.core.models.common import ConfigModel, CoreModel, Duration


class ScalingMetric(str, Enum):
    RPS = "rps"
    # Windowed p90 end-to-end latency (seconds; TTFT for streamed responses)
    # + engine queue depth — the serving-engine control loop
    # (server/services/autoscaler.py).
    LATENCY = "latency"


class ScalingSpec(ConfigModel):
    metric: ScalingMetric = ScalingMetric.RPS
    # rps: target requests/sec per replica. latency: target p90 seconds —
    # p90 above it scales up, p90 under half of it scales down.
    target: float = Field(gt=0)
    # latency metric only: queued requests per replica (reported by the
    # engine via X-Dstack-Queue-Depth) above which a replica is added even
    # while latency still looks healthy — backlog leads latency.
    queue_depth_target: Optional[int] = Field(default=None, ge=1)
    scale_up_delay: Duration = 300
    scale_down_delay: Duration = 600


class RateLimit(ConfigModel):
    prefix: str = "/"
    rps: float = Field(gt=0)
    burst: int = Field(default=1, ge=1)


class ModelFormat(str, Enum):
    OPENAI = "openai"


class ModelSpec(ConfigModel):
    """Registers the service in the OpenAI-compatible model gateway under `name`."""

    name: str
    format: ModelFormat = ModelFormat.OPENAI
    prefix: str = "/v1"

    @model_validator(mode="before")
    @classmethod
    def _parse(cls, v):
        if isinstance(v, str):
            return {"name": v}
        return v


class ServiceSpec(CoreModel):
    """Wire model describing how to reach a deployed service."""

    url: str
    model: Optional[ModelSpec] = None
    options: dict = {}
