"""Plan-time container-image introspection against OCI/Docker registries.

Parity: reference server/services/docker.py:34-70 — resolve the image's
manifest + config (user, entrypoint, platform) with registry auth at plan time,
so a bad `image:`/credential fails in the PLAN instead of after a slice is
provisioned and the pull dies.

SDK-free like the rest of the repo's cloud IO: the Docker Registry HTTP API v2
token dance (WWW-Authenticate -> token endpoint -> Bearer retry) is a small,
stable protocol. Failure policy for air-gapped control planes: a DEFINITIVE
registry answer (404 manifest, 401/403 after the token dance) fails the plan;
a network failure (DNS, refused, timeout) degrades to "unverified" — the
registry may simply be unreachable from the server while reachable from hosts.
"""

from __future__ import annotations

import asyncio
import base64
import json
import re
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional, Tuple

from pydantic import Field

from dstack_tpu.core.errors import ServerClientError
from dstack_tpu.core.models.common import CoreModel

DEFAULT_REGISTRY = "registry-1.docker.io"
MANIFEST_ACCEPT = ", ".join(
    [
        "application/vnd.oci.image.index.v1+json",
        "application/vnd.docker.distribution.manifest.list.v2+json",
        "application/vnd.oci.image.manifest.v1+json",
        "application/vnd.docker.distribution.manifest.v2+json",
    ]
)


class ImageConfig(CoreModel):
    """The subset of the OCI image config the scheduler cares about."""

    image: str
    user: Optional[str] = None
    entrypoint: Optional[list] = None
    cmd: Optional[list] = None
    os: Optional[str] = None
    architecture: Optional[str] = None
    verified: bool = True  # False = registry unreachable, config unknown
    note: Optional[str] = None


def parse_image_ref(image: str) -> Tuple[str, str, str]:
    """image -> (registry_host, repository, reference). Docker-style defaults:
    bare names go to Docker Hub under library/."""
    if not image or not re.match(r"^[\w.\-/:@]+$", image):
        raise ServerClientError(f"invalid image reference: {image!r}")
    digest = None
    if "@" in image:
        image, digest = image.split("@", 1)
    host, _, rest = image.partition("/")
    # A host segment has a dot, a colon (port), or is "localhost"; otherwise the
    # whole string is a Docker Hub repository.
    if rest and ("." in host or ":" in host or host == "localhost"):
        registry = host
        repo_tag = rest
    else:
        registry = DEFAULT_REGISTRY
        repo_tag = image
    if ":" in repo_tag.rsplit("/", 1)[-1]:
        repo, _, tag = repo_tag.rpartition(":")
    else:
        repo, tag = repo_tag, "latest"
    if registry == DEFAULT_REGISTRY and "/" not in repo:
        repo = f"library/{repo}"
    return registry, repo, digest or tag


def _request(url: str, headers: dict, timeout: float = 10.0) -> Tuple[int, dict, bytes]:
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _bearer_challenge(headers: dict) -> Optional[dict]:
    www = next((v for k, v in headers.items() if k.lower() == "www-authenticate"), "")
    if not www.lower().startswith("bearer"):
        return None
    return dict(re.findall(r'(\w+)="([^"]*)"', www))


def _fetch_token(challenge: dict, username: Optional[str], password: Optional[str]) -> Optional[str]:
    realm = challenge.get("realm")
    if not realm:
        return None
    params = {k: v for k, v in challenge.items() if k in ("service", "scope")}
    url = realm + ("?" + urllib.parse.urlencode(params) if params else "")
    headers = {}
    if username:
        basic = base64.b64encode(f"{username}:{password or ''}".encode()).decode()
        headers["Authorization"] = f"Basic {basic}"
    status, _, body = _request(url, headers)
    if status != 200:
        raise ServerClientError(
            f"registry auth failed (HTTP {status} from token endpoint)"
            + (" — check registry_auth credentials" if username else "")
        )
    data = json.loads(body)
    token = data.get("token") or data.get("access_token")
    if not token:
        # A 200 with no token is a malformed token endpoint, not bad creds.
        raise ServerClientError(
            "registry token endpoint returned no token (malformed response)"
        )
    return token


def _get_with_auth(url: str, accept: str, auth_state: dict) -> Tuple[int, dict, bytes]:
    headers = {"Accept": accept}
    if auth_state.get("token"):
        headers["Authorization"] = f"Bearer {auth_state['token']}"
    status, hdrs, body = _request(url, headers)
    if status == 401 and "token" not in auth_state:
        challenge = _bearer_challenge(hdrs)
        if challenge:
            auth_state["token"] = _fetch_token(
                challenge, auth_state.get("username"), auth_state.get("password")
            )
            headers["Authorization"] = f"Bearer {auth_state['token']}"
            status, hdrs, body = _request(url, headers)
    return status, hdrs, body


def _scheme(registry: str, insecure: bool) -> str:
    return "http" if insecure or registry.startswith(("127.", "localhost")) else "https"


def get_image_config_sync(
    image: str,
    username: Optional[str] = None,
    password: Optional[str] = None,
    insecure: bool = False,
) -> ImageConfig:
    registry, repo, ref = parse_image_ref(image)
    base = f"{_scheme(registry, insecure)}://{registry}/v2/{repo}"
    auth: dict = {"username": username, "password": password}
    try:
        return _introspect(image, base, ref, auth)
    except (OSError, urllib.error.URLError) as e:
        # Unreachable network is NOT a bad image: the server may be air-gapped
        # while the TPU hosts are not. This covers ALL hops — manifest, index
        # re-fetch, and the config blob (often a different CDN host than the
        # registry itself). Degrade to unverified.
        return ImageConfig(image=image, verified=False, note=f"registry unreachable: {e}")


def _introspect(image: str, base: str, ref: str, auth: dict) -> ImageConfig:
    status, hdrs, body = _get_with_auth(f"{base}/manifests/{ref}", MANIFEST_ACCEPT, auth)
    if status in (401, 403):
        raise ServerClientError(
            f"not authorized to pull {image} (HTTP {status}) — check registry_auth"
        )
    if status == 404:
        raise ServerClientError(f"image not found in registry: {image}")
    if status != 200:
        raise ServerClientError(f"registry error for {image}: HTTP {status}")
    manifest = json.loads(body)

    # Manifest list / OCI index: prefer linux/amd64 (TPU VMs), else first entry.
    if manifest.get("manifests"):
        entries = manifest["manifests"]
        chosen = next(
            (
                m for m in entries
                if m.get("platform", {}).get("os") == "linux"
                and m.get("platform", {}).get("architecture") == "amd64"
            ),
            entries[0],
        )
        status, _, body = _get_with_auth(
            f"{base}/manifests/{chosen['digest']}", MANIFEST_ACCEPT, auth
        )
        if status != 200:
            raise ServerClientError(f"registry error for {image}: HTTP {status}")
        manifest = json.loads(body)

    config_digest = (manifest.get("config") or {}).get("digest")
    if not config_digest:
        raise ServerClientError(f"unsupported manifest for {image} (no config digest)")
    status, _, body = _get_with_auth(f"{base}/blobs/{config_digest}", "*/*", auth)
    if status != 200:
        raise ServerClientError(f"failed to fetch image config for {image}: HTTP {status}")
    cfg = json.loads(body)
    inner = cfg.get("config") or {}
    return ImageConfig(
        image=image,
        user=inner.get("User") or None,
        entrypoint=inner.get("Entrypoint"),
        cmd=inner.get("Cmd"),
        os=cfg.get("os"),
        architecture=cfg.get("architecture"),
    )


async def get_image_config(
    image: str,
    username: Optional[str] = None,
    password: Optional[str] = None,
    insecure: bool = False,
) -> ImageConfig:
    """Async wrapper: the blocking HTTP dance runs in the default executor."""
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        None, lambda: get_image_config_sync(image, username, password, insecure)
    )


# cache key -> (monotonic_deadline, ImageConfig | ServerClientError).
# Keeps repeated plans fast and avoids hammering registries; definitive errors
# are cached too (a missing tag stays missing for the TTL). The key includes a
# password digest + the insecure flag so that fixing a credential takes effect
# immediately instead of replaying a cached auth failure for the TTL.
_cache: dict = {}
_CACHE_TTL = 300.0


def _cache_key(image, username, password, insecure):
    import hashlib

    pw_digest = hashlib.sha256((password or "").encode()).hexdigest()[:16]
    return (image, username, pw_digest, insecure)


async def get_image_config_cached(
    image: str,
    username: Optional[str] = None,
    password: Optional[str] = None,
    insecure: bool = False,
) -> ImageConfig:
    import time

    key = _cache_key(image, username, password, insecure)
    hit = _cache.get(key)
    if hit and hit[0] > time.monotonic():
        if isinstance(hit[1], Exception):
            raise hit[1]
        return hit[1]
    try:
        result = await get_image_config(image, username, password, insecure)
    except ServerClientError as e:
        _cache[key] = (time.monotonic() + _CACHE_TTL, e)
        raise
    # Unverified (unreachable registry) results are not cached: the outage may
    # be transient and the next plan should retry.
    if result.verified:
        _cache[key] = (time.monotonic() + _CACHE_TTL, result)
    return result


def clear_cache() -> None:
    _cache.clear()
