"""One-request HTTP reverse-proxy forwarding, shared by the in-server service
proxy (server/services/proxy.py) and the gateway appliance (gateway/app.py).

Streams the upstream response chunk-by-chunk, so SSE/chunked inference output
(the OpenAI-compatible streaming path) flows through unbuffered."""

from __future__ import annotations

import logging

import aiohttp
from aiohttp import web

logger = logging.getLogger(__name__)

# Hop-by-hop headers never forwarded (RFC 9110 §7.6.1).
HOP_HEADERS = {
    "connection",
    "keep-alive",
    "proxy-authenticate",
    "proxy-authorization",
    "te",
    "trailers",
    "transfer-encoding",
    "upgrade",
    "host",
    "content-length",
}


async def forward(
    request: web.Request,
    host: str,
    port: int,
    tail: str,
    timeout_total: float = 300.0,
    body: bytes = None,
) -> web.StreamResponse:
    """Forward `request` to http://host:port/<tail> (+query), streaming back."""
    url = f"http://{host}:{port}/{tail.lstrip('/')}"
    if request.query_string:
        url += f"?{request.query_string}"
    headers = {k: v for k, v in request.headers.items() if k.lower() not in HOP_HEADERS}
    if body is None:
        body = await request.read()
    try:
        timeout = aiohttp.ClientTimeout(total=timeout_total)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            async with session.request(
                request.method, url, headers=headers, data=body, allow_redirects=False
            ) as upstream:
                resp = web.StreamResponse(status=upstream.status)
                for k, v in upstream.headers.items():
                    if k.lower() not in HOP_HEADERS:
                        resp.headers[k] = v
                await resp.prepare(request)
                async for chunk in upstream.content.iter_chunked(64 * 1024):
                    await resp.write(chunk)
                await resp.write_eof()
                return resp
    except (aiohttp.ClientError, OSError) as e:
        logger.warning("forward to %s:%s failed: %s", host, port, e)
        raise web.HTTPBadGateway(text="upstream request failed")
