"""One-request HTTP reverse-proxy forwarding, shared by the in-server service
proxy (server/services/proxy.py) and the gateway appliance (gateway/app.py).

Streams the upstream response chunk-by-chunk, so SSE/chunked inference output
(the OpenAI-compatible streaming path) flows through unbuffered.

Upstream connections come from one shared keep-alive ClientSession (lazily
created per event loop): replicas see a warm connection pool instead of a
fresh TCP handshake per request. DSTACK_TPU_PROXY_POOL_SIZE caps concurrent
connections per replica host; the session must be closed on shutdown via
``close_session()`` (the server's cleanup hook and the gateway's serve loop
both do)."""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Callable, Optional

import aiohttp
from aiohttp import web

logger = logging.getLogger(__name__)

# Hop-by-hop headers never forwarded (RFC 9110 §7.6.1).
HOP_HEADERS = {
    "connection",
    "keep-alive",
    "proxy-authenticate",
    "proxy-authorization",
    "te",
    "trailers",
    "transfer-encoding",
    "upgrade",
    "host",
    "content-length",
}

# How long an idle keep-alive connection stays pooled before the connector
# drops it. Short enough that replica churn doesn't accumulate dead sockets.
KEEPALIVE_TIMEOUT = 30.0

DEFAULT_TIMEOUT_TOTAL = 300.0
_DEFAULT_TIMEOUT = aiohttp.ClientTimeout(total=DEFAULT_TIMEOUT_TOTAL)

# Responses with a known Content-Length at or below this are relayed as one
# buffered write instead of the chunk-streaming path — typical JSON inference
# responses skip StreamResponse.prepare + per-chunk writes. SSE/chunked
# output has no Content-Length and always streams, unbuffered.
SMALL_BODY_MAX = 64 * 1024

_session: Optional[aiohttp.ClientSession] = None
_session_loop: Optional[asyncio.AbstractEventLoop] = None
_pooling = True


def pool_size() -> int:
    """Per-replica-host connection cap for the shared session."""
    return int(os.getenv("DSTACK_TPU_PROXY_POOL_SIZE", "100"))


def set_pooling(enabled: bool) -> None:
    """Disable to restore the legacy one-session-per-request path (bench/tests
    measure the pooled path against exactly this)."""
    global _pooling
    _pooling = enabled


def pooling_enabled() -> bool:
    return _pooling


def get_session() -> aiohttp.ClientSession:
    """The shared keep-alive session for the current event loop, created on
    first use. A session left over from a different (test) loop is abandoned —
    its sockets died with that loop — and replaced."""
    global _session, _session_loop
    loop = asyncio.get_running_loop()
    if _session is None or _session.closed or _session_loop is not loop:
        connector = aiohttp.TCPConnector(
            limit=0,  # total is unbounded; per-host is the real knob
            limit_per_host=pool_size(),
            keepalive_timeout=KEEPALIVE_TIMEOUT,
        )
        _session = aiohttp.ClientSession(connector=connector)
        _session_loop = loop
    return _session


async def close_session() -> None:
    """Close the shared session (server shutdown / test teardown). Safe to call
    with no session, and from a different loop than the one that created it
    (the stale session is dropped without touching the dead loop)."""
    global _session, _session_loop
    session, loop = _session, _session_loop
    _session = None
    _session_loop = None
    if session is None or session.closed:
        return
    if loop is asyncio.get_running_loop():
        await session.close()


async def forward(
    request: web.Request,
    host: str,
    port: int,
    tail: str,
    timeout_total: float = DEFAULT_TIMEOUT_TOTAL,
    body: bytes = None,
    on_first_chunk: Optional[Callable[[aiohttp.ClientResponse], None]] = None,
    extra_headers: Optional[dict] = None,
) -> web.StreamResponse:
    """Forward `request` to http://host:port/<tail> (+query), streaming back.

    ``on_first_chunk`` fires once, when the first STREAMED body chunk arrives
    from upstream (buffered known-length responses never call it): for SSE
    token streams that instant is time-to-first-token — the latency signal a
    held-open stream's total duration would poison. The callback gets the
    upstream response (headers readable) and must not raise or block.

    ``extra_headers`` are injected into the UPSTREAM request (overriding any
    same-named client header) — the proxy uses this to stamp its trace id on
    every forwarded request. Upstream response headers flow back to the client
    untouched (minus hop-by-hop), so a replica echoing the trace header is
    visible end to end."""
    url = f"http://{host}:{port}/{tail.lstrip('/')}"
    if request.query_string:
        url += f"?{request.query_string}"
    headers = {k: v for k, v in request.headers.items() if k.lower() not in HOP_HEADERS}
    if extra_headers:
        headers.update(extra_headers)
    if body is None:
        body = await request.read()
    timeout = (
        _DEFAULT_TIMEOUT
        if timeout_total == DEFAULT_TIMEOUT_TOTAL
        else aiohttp.ClientTimeout(total=timeout_total)
    )

    async def _stream(upstream: aiohttp.ClientResponse) -> web.StreamResponse:
        length = upstream.headers.get("Content-Length")
        if length is not None and int(length) <= SMALL_BODY_MAX:
            payload = await upstream.read()
            return web.Response(
                status=upstream.status,
                body=payload,
                headers={
                    k: v
                    for k, v in upstream.headers.items()
                    if k.lower() not in HOP_HEADERS
                },
            )
        resp = web.StreamResponse(status=upstream.status)
        for k, v in upstream.headers.items():
            if k.lower() not in HOP_HEADERS:
                resp.headers[k] = v
        await resp.prepare(request)
        first = on_first_chunk
        async for chunk in upstream.content.iter_chunked(64 * 1024):
            if first is not None:
                try:
                    first(upstream)
                except Exception:
                    logger.exception("on_first_chunk callback failed")
                first = None
            await resp.write(chunk)
        await resp.write_eof()
        return resp

    try:
        if _pooling:
            # Timeout rides on the request, not the shared session: each
            # forwarded request keeps its own budget.
            async with get_session().request(
                request.method, url, headers=headers, data=body,
                allow_redirects=False, timeout=timeout,
            ) as upstream:
                return await _stream(upstream)
        else:
            async with aiohttp.ClientSession(timeout=timeout) as session:
                async with session.request(
                    request.method, url, headers=headers, data=body,
                    allow_redirects=False, timeout=timeout,
                ) as upstream:
                    return await _stream(upstream)
    except (aiohttp.ClientError, OSError, asyncio.TimeoutError) as e:
        logger.warning("forward to %s:%s failed: %s", host, port, e)
        raise web.HTTPBadGateway(text="upstream request failed")
