"""SSH local-forward tunnels over the OpenSSH client.

Parity: reference core/services/ssh/tunnel.py:61-292 (SSHTunnel w/ ProxyJump chains) +
ssh/ports.py (PortsLock). All

control-plane -> instance traffic rides ``ssh -N -L`` forwards: TPU VMs expose no
inbound ports and frequently no external IP (SURVEY §7 hard part (e)).

Differences from the reference: async-first (the tunnel child is supervised with
asyncio, no `-f` daemonization), and the ssh executable is injectable
(``DSTACK_TPU_SSH_BINARY``) so tests substitute a fake ssh that actually forwards
TCP — proving traffic flows through the tunnel without OpenSSH in the image.
"""

from __future__ import annotations

import asyncio
import os
import shutil
import socket
import tempfile
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from dstack_tpu.core.errors import SSHError
from dstack_tpu.core.models.instances import SSHConnectionParams

CONNECT_TIMEOUT = 12.0


def ssh_binary() -> Optional[str]:
    """The OpenSSH client to use, or None when the host has none (direct-HTTP mode)."""
    env = os.getenv("DSTACK_TPU_SSH_BINARY")
    if env:
        return env if os.path.exists(env) else None
    return shutil.which("ssh")


def allocate_local_port() -> int:
    """Bind-to-zero port allocation; the tiny race window until ssh rebinds is
    acceptable (reference PortsLock does the same dance)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class Forward:
    local_port: int
    remote_host: str  # as seen from the SSH destination (usually 127.0.0.1)
    remote_port: int


@dataclass
class SSHTunnel:
    """One ssh child process holding one or more -L forwards to a destination."""

    hostname: str
    username: str = "root"
    port: int = 22
    identity_file: Optional[str] = None
    proxy: Optional[SSHConnectionParams] = None
    forwards: List[Forward] = field(default_factory=list)
    _proc: Optional[asyncio.subprocess.Process] = None

    def command(self, binary: str) -> List[str]:
        cmd = [
            binary,
            "-N",
            "-o", "StrictHostKeyChecking=no",
            "-o", "UserKnownHostsFile=/dev/null",
            "-o", "ExitOnForwardFailure=yes",
            "-o", "ServerAliveInterval=20",
            "-o", "ServerAliveCountMax=3",
            "-o", f"ConnectTimeout={int(CONNECT_TIMEOUT)}",
            "-p", str(self.port),
        ]
        if self.identity_file:
            cmd += ["-i", self.identity_file]
        if self.proxy is not None:
            jump = f"{self.proxy.username}@{self.proxy.hostname}:{self.proxy.port}"
            cmd += ["-J", jump]
        for f in self.forwards:
            cmd += ["-L", f"127.0.0.1:{f.local_port}:{f.remote_host}:{f.remote_port}"]
        cmd.append(f"{self.username}@{self.hostname}")
        return cmd

    @property
    def is_open(self) -> bool:
        return self._proc is not None and self._proc.returncode is None

    async def open(self) -> None:
        binary = ssh_binary()
        if binary is None:
            raise SSHError("no ssh client available")
        if not self.forwards:
            raise SSHError("tunnel opened with no forwards")
        self._proc = await asyncio.create_subprocess_exec(
            *self.command(binary),
            stdin=asyncio.subprocess.DEVNULL,
            stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.PIPE,
        )
        # Ready when every local forward accepts connections (or the child dies).
        deadline = asyncio.get_event_loop().time() + CONNECT_TIMEOUT
        pending = {f.local_port for f in self.forwards}
        while pending:
            if self._proc.returncode is not None:
                stderr = (await self._proc.stderr.read()).decode(errors="replace")
                raise SSHError(
                    f"ssh to {self.hostname} exited {self._proc.returncode}: {stderr[:500]}"
                )
            for port in list(pending):
                if _port_accepts(port):
                    pending.discard(port)
            if not pending:
                break
            if asyncio.get_event_loop().time() > deadline:
                await self.close()
                raise SSHError(f"tunnel to {self.hostname} did not come up")
            await asyncio.sleep(0.05)

    async def close(self) -> None:
        proc, self._proc = self._proc, None
        if proc is not None and proc.returncode is None:
            proc.terminate()
            try:
                await asyncio.wait_for(proc.wait(), timeout=5)
            except asyncio.TimeoutError:
                proc.kill()
                await proc.wait()

    async def __aenter__(self) -> "SSHTunnel":
        await self.open()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()


def _port_accepts(port: int) -> bool:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.settimeout(0.2)
        try:
            s.connect(("127.0.0.1", port))
            return True
        except OSError:
            return False


async def ssh_exec(
    hostname: str,
    command: str,
    *,
    username: str = "root",
    port: int = 22,
    identity_file: Optional[str] = None,
    proxy: Optional[SSHConnectionParams] = None,
    input_data: Optional[bytes] = None,
    timeout: float = 60.0,
) -> Tuple[int, bytes, bytes]:
    """Run one command on a remote host (reference tunnel.py async exec path)."""
    binary = ssh_binary()
    if binary is None:
        raise SSHError("no ssh client available")
    cmd = [
        binary,
        "-o", "StrictHostKeyChecking=no",
        "-o", "UserKnownHostsFile=/dev/null",
        "-o", f"ConnectTimeout={int(CONNECT_TIMEOUT)}",
        "-p", str(port),
    ]
    if identity_file:
        cmd += ["-i", identity_file]
    if proxy is not None:
        cmd += ["-J", f"{proxy.username}@{proxy.hostname}:{proxy.port}"]
    cmd += [f"{username}@{hostname}", command]
    proc = await asyncio.create_subprocess_exec(
        *cmd,
        stdin=asyncio.subprocess.PIPE if input_data is not None else asyncio.subprocess.DEVNULL,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.PIPE,
    )
    try:
        out, err = await asyncio.wait_for(proc.communicate(input_data), timeout=timeout)
    except asyncio.TimeoutError:
        proc.kill()
        await proc.wait()
        raise SSHError(f"ssh command to {hostname} timed out after {timeout}s")
    return proc.returncode or 0, out, err
