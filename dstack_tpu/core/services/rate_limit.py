"""Token-bucket rate limiting for service ingress.

Parity: reference gateway nginx ``limit_req`` zones generated per service
prefix (gateway/services/nginx.py) + RateLimit config (configurations.py:112).
One bucket per (service, prefix); rps refills, burst is the bucket depth."""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple


class TokenBucket:
    def __init__(self, rps: float, burst: int) -> None:
        self.rps = rps
        self.capacity = max(1, burst)
        self.tokens = float(self.capacity)
        self.updated = time.monotonic()

    def allow(self) -> bool:
        now = time.monotonic()
        self.tokens = min(self.capacity, self.tokens + (now - self.updated) * self.rps)
        self.updated = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class RateLimiter:
    """Buckets keyed by (scope, prefix); limits matched longest-prefix-first."""

    def __init__(self) -> None:
        self._buckets: Dict[Tuple[str, str], TokenBucket] = {}

    def check(self, scope: str, path: str, limits: List[dict]) -> bool:
        """True = allowed. `limits` rows: {prefix, rps, burst}."""
        matched: Optional[dict] = None
        for lim in sorted(limits, key=lambda l: -len(l.get("prefix", "/"))):
            if path.startswith(lim.get("prefix", "/")):
                matched = lim
                break
        if matched is None:
            return True
        key = (scope, matched.get("prefix", "/"))
        bucket = self._buckets.get(key)
        burst = int(matched.get("burst", 1))
        # Recreate on ANY config change (rps or burst) so updates apply live.
        if bucket is None or bucket.rps != float(matched["rps"]) or bucket.capacity != max(1, burst):
            bucket = self._buckets[key] = TokenBucket(float(matched["rps"]), burst)
        return bucket.allow()

    def drop_scope(self, scope: str) -> None:
        """Forget every bucket for one service (its run was deleted)."""
        for key in [k for k in self._buckets if k[0] == scope]:
            del self._buckets[key]

    def reset(self) -> None:
        self._buckets.clear()
