"""Shared request-stats window constants.

The control plane's autoscaler (server/services/proxy.py) and the gateway
appliance (gateway/app.py) must agree on bucket granularity: the server
interprets the appliance's wall-clock bucket keys with these values when it
pulls gateway request stats into the scaling window.
"""

STATS_WINDOW = 600.0  # seconds of request history kept per service
STATS_BUCKET = 10.0  # bucket granularity (seconds)
