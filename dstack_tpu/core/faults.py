"""Fault injection points for chaos testing the control plane.

Named call sites (``runner.request``, ``backend.create_slice``,
``backend.update``, ``proxy.forward``) call :func:`check` before doing real
work; an active fault spec makes a configured fraction of those calls fail
(:class:`FaultInjected`) and/or stall. The caller converts the injection into
its site's natural failure type (RunnerError, BackendError, a 502), so the
whole production failure path downstream of the injection point is exercised —
disconnect grace windows, gang retries, circuit breakers, lease reclaim.

Configuration, in precedence order:

1. ``configure(spec)`` — programmatic (bench_chaos, tests).
2. ``DSTACK_TPU_FAULTS`` — a JSON spec in the environment.
3. ``DSTACK_TPU_FAULTS_FILE`` — path to a JSON spec re-read when its mtime
   changes (flip faults on a LIVE server by editing the file; throttled to
   one stat per second).

Spec shape::

    {"seed": 7,
     "sites": {
        "runner.request":       {"fail": 0.2, "error": "injected agent drop"},
        "backend.create_slice": {"fail": 0.5, "times": 6},
        "proxy.forward":        {"fail": 1.0, "match": ":8801"},
        "backend.update":       {"delay": 0.2, "delay_p": 0.5}}}

Per site: ``fail`` — probability a call raises; ``delay``/``delay_p`` —
stall seconds and the probability of stalling; ``times`` — total injection
budget (delays + failures) after which the site goes quiet; ``match`` —
substring the call's detail must contain; ``error`` — message carried by the
raised FaultInjected. ``seed`` makes a schedule reproducible. The whole module
is a no-op (one dict lookup) when nothing is configured — production hot paths
pay nothing.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import threading
import time
from typing import Dict, Optional

__all__ = ["FaultInjected", "check", "configure", "clear", "active", "stats"]


class FaultInjected(Exception):
    """Raised by an injection point; callers convert to their native error."""

    def __init__(self, site: str, message: str = ""):
        super().__init__(message or f"fault injected at {site}")
        self.site = site


_lock = threading.Lock()
_spec: Optional[dict] = None          # programmatic spec (configure())
_env_spec: Optional[dict] = None      # parsed DSTACK_TPU_FAULTS cache
_env_raw: Optional[str] = None
_file_spec: Optional[dict] = None     # parsed DSTACK_TPU_FAULTS_FILE cache
_file_mtime: Optional[float] = None
_file_checked_at: float = 0.0
_rng = random.Random()
_counts: Dict[str, int] = {}
_budget: Dict[str, int] = {}


def _normalize(spec: dict) -> dict:
    sites = spec.get("sites", spec)  # bare {site: conf} accepted
    return {"seed": spec.get("seed"), "sites": dict(sites)}


def configure(spec: Optional[dict]) -> None:
    """Install a fault spec programmatically (None removes it). Resets the
    per-site counters/budgets and reseeds the schedule."""
    global _spec
    with _lock:
        _spec = _normalize(spec) if spec else None
        _counts.clear()
        _budget.clear()
        if _spec and _spec.get("seed") is not None:
            _rng.seed(_spec["seed"])


def clear() -> None:
    configure(None)


def _current_spec() -> Optional[dict]:
    global _env_spec, _env_raw, _file_spec, _file_mtime, _file_checked_at
    if _spec is not None:
        return _spec
    raw = os.getenv("DSTACK_TPU_FAULTS")
    if raw:
        if raw != _env_raw:
            try:
                _env_spec = _normalize(json.loads(raw))
                if _env_spec.get("seed") is not None:
                    _rng.seed(_env_spec["seed"])
            except ValueError:
                _env_spec = None
            _env_raw = raw
        return _env_spec
    path = os.getenv("DSTACK_TPU_FAULTS_FILE")
    if path:
        now = time.monotonic()
        if now - _file_checked_at >= 1.0:
            _file_checked_at = now
            try:
                mtime = os.stat(path).st_mtime
            except OSError:
                _file_spec, _file_mtime = None, None
                return None
            if mtime != _file_mtime:
                _file_mtime = mtime
                try:
                    with open(path) as f:
                        _file_spec = _normalize(json.load(f))
                    if _file_spec.get("seed") is not None:
                        _rng.seed(_file_spec["seed"])
                except (OSError, ValueError):
                    _file_spec = None
        return _file_spec
    return None


def active() -> bool:
    return _current_spec() is not None


def stats() -> Dict[str, int]:
    """Injections delivered so far, by site (chaos-bench reporting)."""
    with _lock:
        return dict(_counts)


def _consume_budget(site: str, conf: dict) -> bool:
    times = conf.get("times")
    if times is None:
        return True
    with _lock:
        left = _budget.get(site, int(times))
        if left <= 0:
            return False
        _budget[site] = left - 1
    return True


def _count(site: str) -> None:
    with _lock:
        _counts[site] = _counts.get(site, 0) + 1


async def check(site: str, detail: str = "") -> None:
    """Injection point. May sleep (delay faults) and/or raise FaultInjected.
    A no-op unless a spec names this site (and its ``match`` hits `detail`)."""
    spec = _current_spec()
    if spec is None:
        return
    conf = spec["sites"].get(site)
    if conf is None:
        return
    match = conf.get("match")
    if match and match not in detail:
        return
    delay = conf.get("delay")
    if delay and _rng.random() < conf.get("delay_p", 1.0):
        if _consume_budget(site, conf):
            _count(site)
            await asyncio.sleep(float(delay))
    p = conf.get("fail", 0.0)
    if p and _rng.random() < p:
        if _consume_budget(site, conf):
            _count(site)
            raise FaultInjected(site, conf.get("error", "") or f"{site} {detail}".strip())
