"""Framework exception hierarchy (parity: /root/reference src/dstack/_internal/core/errors.py)."""


class DstackTpuError(Exception):
    """Base for all framework errors."""


class ConfigurationError(DstackTpuError):
    """Invalid user-supplied configuration."""


class ServerClientError(DstackTpuError):
    """Error reported by the server to a client; carries an HTTP-friendly message."""

    code = "error"

    def __init__(self, msg: str = ""):
        super().__init__(msg)
        self.msg = msg


class ResourceNotExistsError(ServerClientError):
    code = "resource_not_exists"


class ResourceExistsError(ServerClientError):
    code = "resource_exists"


class ForbiddenError(ServerClientError):
    code = "forbidden"


class NotAuthenticatedError(ServerClientError):
    code = "not_authenticated"


class BackendError(DstackTpuError):
    """Cloud backend failure."""


class NoCapacityError(BackendError):
    """No offers/capacity available to provision."""


class ComputeError(BackendError):
    """Provisioning call failed."""


class PlacementGroupInUseError(BackendError):
    pass


class SSHError(DstackTpuError):
    pass


class GatewayError(DstackTpuError):
    pass
