"""Zero-dependency run lifecycle tracer: contextvar trace ids, timed spans,
in-process fixed-bucket duration histograms, and named gauges.

The control plane's question is "where did my run spend its time?". This module
answers the in-process half: every scheduler pass, offer query, backend
provisioning call, runner round trip, and proxied request runs under a
``span(...)`` whose duration lands in a histogram that
``server/services/prometheus.py`` renders as ``_bucket``/``_sum``/``_count``
series. The persistent half (the ``run_events`` table) lives in
``server/services/events.py``; it stamps each row with the current trace id so
a slow span in the logs is joinable to the run timeline.

Design constraints:
- core must not import server code (the gateway appliance uses core too), so
  the slow-span threshold is read straight from the environment
  (``DSTACK_TPU_TRACE_SLOW_SECONDS``, default 5.0; 0 disables the warning).
- observations may come from the DB worker thread (event writes happen inside
  transactions), so the registries are guarded by a lock. The hot proxy path
  only appends to an in-memory list under that lock — no DB, no syscalls.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import os
import threading
import time
import uuid
from typing import Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)

# Default buckets span the control plane's dynamic range: single-digit-ms proxy
# forwards up to multi-minute cloud provisioning. Fixed (not per-family) so the
# exposition stays stable and dashboards can be written once.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0, 600.0,
)

# The header carrying a trace id across process hops: runner client -> agent
# (already), and service proxy -> serving replica (ISSUE 18). One constant so
# every hop agrees on the spelling.
TRACE_HEADER = "X-Dstack-Trace-Id"

_trace_id: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "dstack_tpu_trace_id", default=None
)
_span_id: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "dstack_tpu_span_id", default=None
)


def slow_span_threshold() -> float:
    try:
        return float(os.getenv("DSTACK_TPU_TRACE_SLOW_SECONDS", "5.0"))
    except ValueError:
        return 5.0


def new_trace() -> str:
    """Start a fresh trace (one scheduler work item, one API request); returns
    the new trace id and binds it to the current context."""
    tid = uuid.uuid4().hex[:16]
    _trace_id.set(tid)
    _span_id.set(None)
    return tid


def set_trace_id(trace_id: str) -> str:
    """Adopt an externally-minted trace id (e.g. the proxy's
    ``X-Dstack-Trace-Id`` header arriving at a serving replica) as the current
    context's trace, so spans and logs on this side join the caller's trace."""
    _trace_id.set(trace_id)
    _span_id.set(None)
    return trace_id


def wrap_with_context(fn):
    """Capture the CALLER's contextvars (trace/span ids included) and return a
    callable running ``fn`` inside that snapshot.

    ``contextvars`` don't cross thread boundaries: a ``threading.Thread``
    target starts from an empty context, so a trace id bound before spawning
    an engine worker thread silently vanishes inside it. Wrap the thread
    target with this at construction time — the snapshot is taken HERE, not at
    call time — and the spawned thread observes the spawner's trace."""
    ctx = contextvars.copy_context()

    def _in_context(*args, **kwargs):
        return ctx.run(fn, *args, **kwargs)

    return _in_context


def current_trace_id() -> Optional[str]:
    return _trace_id.get()


def current_span_id() -> Optional[str]:
    return _span_id.get()


class Histogram:
    """Cumulative fixed-bucket histogram (Prometheus semantics), one counter
    vector per label set."""

    __slots__ = ("name", "buckets", "_series")

    def __init__(self, name: str, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        # label-items tuple -> [bucket_counts..., +Inf count, sum]
        self._series: Dict[Tuple[Tuple[str, str], ...], List[float]] = {}

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        key = tuple(sorted((labels or {}).items()))
        row = self._series.get(key)
        if row is None:
            row = self._series[key] = [0.0] * (len(self.buckets) + 2)
        for i, le in enumerate(self.buckets):
            if value <= le:
                row[i] += 1
        row[-2] += 1  # +Inf / total count
        row[-1] += value  # sum

    def snapshot(self) -> List[Tuple[Dict[str, str], List[float], float, float]]:
        """[(labels, cumulative_bucket_counts incl +Inf, sum, count)]."""
        out = []
        for key, row in sorted(self._series.items()):
            out.append((dict(key), list(row[:-1]), row[-1], row[-2]))
        return out


_lock = threading.Lock()
_histograms: Dict[str, Histogram] = {}
_gauges: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}


def observe(name: str, seconds: float, labels: Optional[Dict[str, str]] = None) -> None:
    """Record one duration into the named histogram (thread-safe)."""
    with _lock:
        hist = _histograms.get(name)
        if hist is None:
            hist = _histograms[name] = Histogram(name)
        hist.observe(seconds, labels)


def histogram_snapshot(name: str):
    """Snapshot of one histogram family, or None if never observed."""
    with _lock:
        hist = _histograms.get(name)
        return None if hist is None else (hist.buckets, hist.snapshot())


def histogram_names() -> List[str]:
    with _lock:
        return sorted(_histograms)


def drop_series(name: str, labels: Dict[str, str]) -> None:
    """Remove one histogram series (exact label match). Per-run series (e.g.
    proxied latency labeled by run name) must go when the run goes, or
    /metrics grows one dead series per run ever observed."""
    with _lock:
        hist = _histograms.get(name)
        if hist is not None:
            hist._series.pop(tuple(sorted(labels.items())), None)


def set_gauge(name: str, labels: Optional[Dict[str, str]], value: float) -> None:
    with _lock:
        _gauges[(name, tuple(sorted((labels or {}).items())))] = value


def gauge_snapshot(name: str) -> List[Tuple[Dict[str, str], float]]:
    with _lock:
        return [
            (dict(key[1]), v) for key, v in sorted(_gauges.items()) if key[0] == name
        ]


def summary(name: str, labels: Optional[Dict[str, str]] = None) -> Optional[dict]:
    """{count, mean, p50, p90, max_bucket} estimated from the histogram —
    bench.py records these so BENCH_* files carry distributions, not means."""
    snap = histogram_snapshot(name)
    if snap is None:
        return None
    buckets, series = snap
    want = tuple(sorted((labels or {}).items()))
    rows = [r for r in series if tuple(sorted(r[0].items())) == want or labels is None]
    if not rows:
        return None
    # Merge matching series (labels=None merges all of them).
    counts = [0.0] * (len(buckets) + 1)
    total_sum = 0.0
    total_count = 0.0
    for _, cum, s, c in rows:
        for i, v in enumerate(cum):
            counts[i] += v
        total_sum += s
        total_count += c
    if total_count == 0:
        return None

    def quantile(q: float) -> float:
        target = q * total_count
        for i, le in enumerate(buckets):
            if counts[i] >= target:
                return le
        return float("inf")

    return {
        "count": int(total_count),
        "mean": total_sum / total_count,
        "p50": quantile(0.5),
        "p90": quantile(0.9),
    }


def reset() -> None:
    """Drop all registered histograms and gauges (tests/bench isolation)."""
    with _lock:
        _histograms.clear()
        _gauges.clear()


def _esc_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render_exposition(help_map: Optional[Dict[str, str]] = None) -> str:
    """Prometheus text exposition of every registered histogram — the
    replica-local ``/metrics`` surface (a serving engine runs in its own
    process; the control plane's ``server/services/prometheus.py`` can't see
    this registry). Families named in ``help_map`` are advertised (HELP/TYPE)
    even before the first observation; format matches the server renderer, so
    the same strict parser validates both."""
    names = list(help_map or {})
    for name in histogram_names():
        if name not in names:
            names.append(name)
    lines: List[str] = []
    for name in names:
        help_ = (help_map or {}).get(name, f"Span duration for {name}")
        lines.append(f"# HELP {name} " + help_.replace("\\", "\\\\").replace("\n", "\\n"))
        lines.append(f"# TYPE {name} histogram")
        snap = histogram_snapshot(name)
        if snap is None:
            continue
        buckets, series = snap
        for labels, cumulative, total, count in series:
            for le, c in zip([f"{b:g}" for b in buckets] + ["+Inf"], cumulative):
                inner = ",".join(
                    f'{k}="{_esc_label(v)}"'
                    for k, v in sorted({**labels, "le": le}.items())
                )
                lines.append(f"{name}_bucket{{{inner}}} {c:g}")
            if labels:
                inner = ",".join(
                    f'{k}="{_esc_label(v)}"' for k, v in sorted(labels.items())
                )
                lines.append(f"{name}_sum{{{inner}}} {total:g}")
                lines.append(f"{name}_count{{{inner}}} {count:g}")
            else:
                lines.append(f"{name}_sum {total:g}")
                lines.append(f"{name}_count {count:g}")
    return "\n".join(lines) + "\n"


@contextlib.contextmanager
def span(
    name: str,
    histogram: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    **attrs,
) -> Iterator[None]:
    """Timed span: propagates trace/span ids through the context, feeds the
    duration into ``histogram`` (when given), and WARNs when the span exceeds
    DSTACK_TPU_TRACE_SLOW_SECONDS. ``attrs`` (e.g. ``run="name"``) only appear
    in the slow-span log line — they never become metric labels, so arbitrary
    run names can't explode exposition cardinality.

    Works around both sync and async code: the context manager holds no lock
    across the body, and the ids restore on exit even when the body raises."""
    if _trace_id.get() is None:
        new_trace()
    parent = _span_id.get()
    sid = uuid.uuid4().hex[:8]
    token = _span_id.set(sid)
    t0 = time.monotonic()
    try:
        yield
    finally:
        elapsed = time.monotonic() - t0
        _span_id.reset(token)
        if histogram is not None:
            observe(histogram, elapsed, labels)
        threshold = slow_span_threshold()
        if threshold > 0 and elapsed >= threshold:
            extra = " ".join(f"{k}={v}" for k, v in attrs.items())
            logger.warning(
                "slow span %s: %.2fs (trace=%s span=%s parent=%s)%s",
                name, elapsed, _trace_id.get(), sid, parent or "-",
                f" {extra}" if extra else "",
            )
