"""Client/server API compatibility (parity: reference core/compatibility/ +
check_client_server_compatibility, app.py:273-286).

The wire protocol is versioned by major: clients send ``x-api-version``; the
server rejects a different MAJOR with a clear error and ignores minor/patch
drift (pydantic models tolerate unknown fields on input and clients must treat
unknown response fields the same way — that IS the minor-version contract).
Requests without the header (curl, browsers, probes) pass."""

from __future__ import annotations

from typing import Optional, Tuple

API_VERSION = "1.0"
API_VERSION_HEADER = "x-api-version"


def parse_version(v: str) -> Optional[Tuple[int, int]]:
    parts = v.strip().split(".")
    try:
        return int(parts[0]), int(parts[1]) if len(parts) > 1 else 0
    except (ValueError, IndexError):
        return None


def check_client_version(client_version: Optional[str]) -> Optional[str]:
    """None when compatible; an error message otherwise."""
    if not client_version:
        return None
    client = parse_version(client_version)
    if client is None:
        return f"unparsable {API_VERSION_HEADER}: {client_version!r}"
    server = parse_version(API_VERSION)
    if client[0] != server[0]:
        return (
            f"client API version {client_version} is incompatible with server"
            f" API version {API_VERSION}; upgrade the older side"
        )
    return None
