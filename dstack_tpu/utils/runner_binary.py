"""Locate (and build on demand) the native dstack-tpu-runner binary.

Parity: the reference downloads prebuilt Go runner binaries from S3
(base/compute.py:612-628); here the C++ agent ships in-tree (runner/) and is compiled
once per host with make."""

from __future__ import annotations

import os
import shutil
import subprocess
import threading
from pathlib import Path
from typing import Optional

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_RUNNER_DIR = _REPO_ROOT / "runner"
_BINARY = _RUNNER_DIR / "build" / "dstack-tpu-runner"
_build_lock = threading.Lock()


def find_runner_binary(build: bool = True) -> Optional[str]:
    env_path = os.getenv("DSTACK_TPU_RUNNER_BINARY")
    if env_path and Path(env_path).exists():
        return env_path
    if _BINARY.exists():
        return str(_BINARY)
    on_path = shutil.which("dstack-tpu-runner")
    if on_path:
        return on_path
    if build and (_RUNNER_DIR / "Makefile").exists() and shutil.which("make"):
        with _build_lock:
            if _BINARY.exists():
                return str(_BINARY)
            try:
                # File lock so concurrent *processes* (server + tests) don't race the
                # same build directory; the threading.Lock only covers this process.
                import fcntl

                lock_path = _RUNNER_DIR / ".build.lock"
                with open(lock_path, "w") as lock_file:
                    fcntl.flock(lock_file, fcntl.LOCK_EX)
                    try:
                        if not _BINARY.exists():
                            subprocess.run(
                                ["make", "-C", str(_RUNNER_DIR)],
                                check=True,
                                capture_output=True,
                                timeout=300,
                            )
                    finally:
                        fcntl.flock(lock_file, fcntl.LOCK_UN)
            except (subprocess.CalledProcessError, subprocess.TimeoutExpired, OSError):
                return None
        if _BINARY.exists():
            return str(_BINARY)
    return None
