"""Small shared utilities (parity: reference _internal/utils/common.py)."""

from __future__ import annotations

import datetime
from typing import Optional


def now_utc() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def to_iso(dt: Optional[datetime.datetime]) -> Optional[str]:
    if dt is None:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.astimezone(datetime.timezone.utc).isoformat()


def from_iso(s: Optional[str]) -> Optional[datetime.datetime]:
    if s is None:
        return None
    dt = datetime.datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt


def pretty_resources_duration(seconds: float) -> str:
    seconds = int(seconds)
    if seconds < 60:
        return f"{seconds}s"
    if seconds < 3600:
        return f"{seconds // 60}m {seconds % 60}s"
    return f"{seconds // 3600}h {(seconds % 3600) // 60}m"
