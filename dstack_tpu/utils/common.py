"""Small shared utilities (parity: reference _internal/utils/common.py)."""

from __future__ import annotations

import datetime
from typing import Optional


def now_utc() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def to_iso(dt: Optional[datetime.datetime]) -> Optional[str]:
    if dt is None:
        return None
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt.astimezone(datetime.timezone.utc).isoformat()


def from_iso(s: Optional[str]) -> Optional[datetime.datetime]:
    if s is None:
        return None
    # Python < 3.11 fromisoformat rejects the RFC 3339 'Z' suffix, which is
    # exactly what pydantic's JSON serializer emits — clients echoing our own
    # timestamps back (keyset-pagination cursors) must round-trip.
    if isinstance(s, str) and s.endswith(("Z", "z")):
        s = s[:-1] + "+00:00"
    dt = datetime.datetime.fromisoformat(s)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return dt


def nearest_rank(sorted_samples, q: float):
    """Nearest-rank percentile over an ascending list (q in [0, 1]); the one
    definition shared by the autoscaler's latency window and the serve bench
    so their p50/p90/p99 never silently diverge. None for an empty list."""
    if not sorted_samples:
        return None
    return sorted_samples[min(len(sorted_samples) - 1, int(q * len(sorted_samples)))]


def pretty_resources_duration(seconds: float) -> str:
    seconds = int(seconds)
    if seconds < 60:
        return f"{seconds}s"
    if seconds < 3600:
        return f"{seconds // 60}m {seconds % 60}s"
    return f"{seconds // 3600}h {(seconds % 3600) // 60}m"
