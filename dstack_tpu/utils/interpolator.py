"""``${{ namespace.name }}`` variable interpolation.

Parity: reference _internal/utils/interpolator.py (VariablesInterpolator), used by
process_running_jobs to resolve ``${{ secrets.X }}`` in job env values. Only values the
run configuration explicitly references are resolved — secrets are never injected
wholesale into a job's environment.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Mapping, Set

_PATTERN = re.compile(r"\$\{\{\s*([A-Za-z_][A-Za-z0-9_]*)\.([A-Za-z_][A-Za-z0-9_-]*)\s*\}\}")


class InterpolatorError(ValueError):
    pass


def extract_references(values: Iterable[str], namespace: str) -> Set[str]:
    """Names referenced as ``${{ namespace.name }}`` across the given strings."""
    found: Set[str] = set()
    for value in values:
        if not isinstance(value, str):
            continue
        for m in _PATTERN.finditer(value):
            if m.group(1) == namespace:
                found.add(m.group(2))
    return found


def interpolate(
    value: str,
    namespaces: Mapping[str, Mapping[str, str]],
    *,
    missing_ok: bool = False,
) -> str:
    """Replace every ``${{ ns.name }}`` occurrence with namespaces[ns][name].

    Unknown namespaces are left untouched (they may belong to a later resolution
    stage); unknown names in a known namespace raise unless ``missing_ok``.
    """

    def repl(m: re.Match) -> str:
        ns, name = m.group(1), m.group(2)
        if ns not in namespaces:
            return m.group(0)
        values = namespaces[ns]
        if name not in values:
            if missing_ok:
                return m.group(0)
            raise InterpolatorError(f"unknown {ns} variable {name!r}")
        return values[name]

    return _PATTERN.sub(repl, value)


def interpolate_env(
    env: Mapping[str, str],
    namespaces: Mapping[str, Mapping[str, str]],
    *,
    missing_ok: bool = False,
) -> Dict[str, str]:
    return {
        k: interpolate(v, namespaces, missing_ok=missing_ok) if isinstance(v, str) else v
        for k, v in env.items()
    }
