"""Server SSH identity: ed25519 keypair generated on first use.

Parity: reference utils/crypto.py (RSA keygen for project keys) — ed25519 here
(smaller, modern default), serialized in OpenSSH format via ``cryptography``
when that wheel is installed, or the OpenSSH ``ssh-keygen`` binary otherwise
(the images this repo targets ship the OpenSSH client suite for the tunnel
layer but not the cryptography wheel — returning an empty key here silently
skipped authorized_keys installation on SSH fleets, so every healthcheck
tunnel died at auth and hosts were torn down at PROVISIONING_TIMEOUT).
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from pathlib import Path
from typing import Tuple


def _generate_with_cryptography() -> Tuple[str, str]:
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519

    key = ed25519.Ed25519PrivateKey.generate()
    private = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.OpenSSH,
        serialization.NoEncryption(),
    ).decode()
    public = (
        key.public_key()
        .public_bytes(serialization.Encoding.OpenSSH, serialization.PublicFormat.OpenSSH)
        .decode()
        + " dstack-tpu-server"
    )
    return private, public


def _generate_with_ssh_keygen() -> Tuple[str, str]:
    with tempfile.TemporaryDirectory() as tmp:
        path = Path(tmp) / "id_ed25519"
        subprocess.run(
            ["ssh-keygen", "-t", "ed25519", "-N", "", "-q",
             "-C", "dstack-tpu-server", "-f", str(path)],
            check=True,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE,
        )
        return path.read_text(), path.with_suffix(".pub").read_text().strip()


def generate_ed25519_keypair() -> Tuple[str, str]:
    """Returns (private_key_openssh, public_key_line)."""
    try:
        return _generate_with_cryptography()
    except ImportError:
        return _generate_with_ssh_keygen()


def get_server_ssh_keypair(server_dir: Path) -> Tuple[str, str]:
    """(identity_file_path, public_key_line); generated under server_dir/ssh once."""
    ssh_dir = server_dir / "ssh"
    private_path = ssh_dir / "id_ed25519"
    public_path = ssh_dir / "id_ed25519.pub"
    if not private_path.exists():
        ssh_dir.mkdir(parents=True, exist_ok=True)
        private, public = generate_ed25519_keypair()
        private_path.write_text(private)
        os.chmod(private_path, 0o600)
        public_path.write_text(public + "\n")
    return str(private_path), public_path.read_text().strip()
