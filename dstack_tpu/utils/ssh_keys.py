"""Server SSH identity: ed25519 keypair generated on first use.

Parity: reference utils/crypto.py (RSA keygen for project keys) — ed25519 here
(smaller, modern default), serialized in OpenSSH format via ``cryptography``.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Tuple


def generate_ed25519_keypair() -> Tuple[str, str]:
    """Returns (private_key_openssh, public_key_line)."""
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519

    key = ed25519.Ed25519PrivateKey.generate()
    private = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.OpenSSH,
        serialization.NoEncryption(),
    ).decode()
    public = (
        key.public_key()
        .public_bytes(serialization.Encoding.OpenSSH, serialization.PublicFormat.OpenSSH)
        .decode()
        + " dstack-tpu-server"
    )
    return private, public


def get_server_ssh_keypair(server_dir: Path) -> Tuple[str, str]:
    """(identity_file_path, public_key_line); generated under server_dir/ssh once."""
    ssh_dir = server_dir / "ssh"
    private_path = ssh_dir / "id_ed25519"
    public_path = ssh_dir / "id_ed25519.pub"
    if not private_path.exists():
        ssh_dir.mkdir(parents=True, exist_ok=True)
        private, public = generate_ed25519_keypair()
        private_path.write_text(private)
        os.chmod(private_path, 0o600)
        public_path.write_text(public + "\n")
    return str(private_path), public_path.read_text().strip()
