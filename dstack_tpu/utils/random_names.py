"""Memorable run-name generator (parity: reference _internal/utils/random_names.py —
different word lists, same adjective-noun-number shape)."""

from __future__ import annotations

import random

_ADJECTIVES = [
    "swift", "calm", "bright", "brave", "quiet", "rapid", "solid", "vivid", "lucid",
    "noble", "eager", "merry", "keen", "bold", "wise", "fond", "warm", "cool", "deft",
    "spry", "sleek", "stout", "sunny", "tidy", "agile", "amber", "azure", "coral",
    "ivory", "jade", "onyx", "pearl", "ruby", "topaz", "cobalt",
]

_NOUNS = [
    "falcon", "otter", "heron", "lynx", "puffin", "marmot", "ibex", "gecko", "wren",
    "stork", "tern", "dingo", "tapir", "quokka", "lemur", "hare", "mole", "vole",
    "newt", "koi", "crane", "finch", "swift2", "raven", "magpie", "osprey", "kestrel",
    "plover", "sparrow", "weasel", "badger", "beaver", "bison", "camel", "donkey",
]


def generate_name(rng: random.Random = random) -> str:
    adj = rng.choice(_ADJECTIVES)
    noun = rng.choice(_NOUNS).rstrip("0123456789")
    return f"{adj}-{noun}-{rng.randint(1, 99)}"
